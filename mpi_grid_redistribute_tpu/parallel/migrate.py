"""Resident-state migration: the fast drift-loop exchange (SURVEY.md §3.3).

The general :mod:`exchange` path re-packs every particle into canonical MPI
``Alltoallv`` receive order each step — full-array gathers plus a pool-wide
stable sort. (Its WIRE cost is now also mover-scaled: the count-driven
``sparse``/``neighbor`` canonical engines in :mod:`exchange` ship
``mover_cap``-wide pools over ``all_to_all``/``ppermute`` with an
in-graph dense fallback — this module keeps the mover-scaled COMPUTE
story for resident-slot state.) Profiling on the real chip shows the
true TPU cost model:

  * random-access scatter costs ~76-85 ns *per row* regardless of row width
    (measured in BOTH layouts; see below) — scatters must be few and sized
    to the data actually moved;
  * ``segment_sum`` histograms lower to scatter-add (~37 ms at 4M) — counts
    must come from ``searchsorted`` on already-sorted keys instead;
  * a full stable sort of 4M int32 keys is ~6 ms; elementwise binning ~3 ms.

**Planar layout** (round 3): the fused state is carried TRANSPOSED —
``[K, n]``, components on the sublane axis, particles on the lane
axis — because TPU stores any narrow-minor ``[n, K]`` buffer that
materializes at a program boundary or scan carry in the tiled ``T(8,128)``
layout: ``[n, 7]`` pads 128/7 = 18x (32 GB at 64M rows — the round-2 cap
on the single-chip north-star run). ``[K, n]`` pads only 8/ceil(K) on the
sublane axis (1.14x at K=7). Measured layout costs on the v5e-class chip
(scripts/microbench_layout.py, n=8.4M, P=262k): column gather 25.2 vs row
gather 17.6 ns/row; column scatter 76.1 vs row scatter 84.8 ns/row —
i.e. the planar layout is performance-neutral for the hot ops while
removing the 18x memory padding entirely.

Design (one compiled step, all static shapes):

  1. bin -> ``leaving`` mask (alive rows whose owner changed);
  2. ONE stable key sort groups leaving rows by destination; per-destination
     counts fall out of ``searchsorted`` on the sorted keys (no scatter-add);
  3. migrants beyond the per-(source,dest) ``capacity`` — or beyond what
     the receiver GRANTS (below) — simply STAY resident and retry next
     step (surfaced as ``backlog``; particles are never dropped);
  4. receiver-side flow control makes the receive lossless: desired
     per-pair counts fly first, each receiver grants pairwise swaps
     (self-financing: a swap arrival's matching departure vacates a slot)
     plus a greedy share of its free slots, grants fly back, and only
     granted rows are packed — arrivals are structurally bounded by what
     can land;
  5. one fused ``[R, K, C]`` ``lax.all_to_all`` moves position + payload +
     alive row as a single INT32 matrix (everything bitcast — round 4:
     integer transport is what keeps bit patterns exact on TPU vector
     units, whose float chains flush denormal patterns; see
     :func:`fuse_fields`);
  6. arrivals land exactly in the slots vacated by departures, then in slots
     popped from a carried free-slot *stack* (contiguous dynamic-slice
     push/pop — never a scatter); one single scatter per step writes
     payload, alive flag, and vacancy markers together; ``dropped_recv``
     remains as a surfaced safety counter and is structurally zero.

**Rotation-cycle liveness** (round-3; was a documented stall in round 2):
the least fixpoint of the self-financing grant recursion is zero on a pure
rotation cycle of length >= 3 between COMPLETELY full shards at zero free
slots — pairwise swaps are zero and there is nothing to grant. Both paths
now detect such cycles (:func:`_cycle_rescue`: functional graph of first
pending destinations over totally-stalled shards, boolean-closure cycle
detection) and force ONE granted row along each cycle edge per step; the
forced arrival lands in the slot the member's own forced departure
vacates, so the rescue is lossless with zero free slots and the cycle
drains at one row per member per step. Round 4 closed the last gap:
cycles that SPAN devices on the vrank path are rescued too — the global
pending matrix is all_gathered (it is O(R_total^2) ints and already
crosses the wire in spirit during the grant phase), the same closure
runs on it, and the forced cross-device arrivals are financed through
the free-slot stack (the forced departure's vacated slot is pushed by
the local landing phase and popped by the remote landing that follows).
Above 128 global ranks the global pass is disabled (R^2 log R closure
cost, same bound as the flat engine) and the per-device rescue remains.

**Virtual ranks** (:func:`shard_migrate_vranks_fn`): each device can host a
whole sub-grid of subdomains ("vranks", slabs side by side on the lane
axis), so a 4x4x4 grid runs on 8 chips — or on one — with identical
semantics: the cross-device hop is one ``lax.all_to_all`` on the
``[Dev, V_src, V_dst, K, C]`` buffer; vrank-to-vrank traffic on the same
device never leaves HBM. This is the TPU answer to running an R-rank MPI
job on fewer nodes (SURVEY.md §2 process-grid topology, §7.6 scale).

Slot order is *not* the MPI canonical order — arrivals fill arbitrary holes.
Correctness is therefore set-equality per shard against the oracle (tested
at the BIT level: the engine only ever moves rows), not order-equality; use
:mod:`exchange` when canonical MPI receive order matters.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.ops.pack import pack_cols as _pack_cols
from mpi_grid_redistribute_tpu.ops.pack import (
    gather_plan_cols as _gather_plan_cols,
)
# mig:bin / mig:pack / mig:exchange / mig:unpack named scopes on the step
# phases — XLA op metadata for Perfetto/XProf grouping (telemetry.phases)
from mpi_grid_redistribute_tpu.telemetry.phases import traced_span


def _resolve_scatter_impl(scatter_impl) -> str:
    """Resolve the landing-scatter implementation choice at BUILD time.

    Returns one of ``"overlay"`` (default on TPU: the planar one-hot
    overlay kernel, ops/pallas_overlay — measured 2.6x the XLA scatter at
    bench shapes), ``"xla"``, or ``"rows"`` (the round-2 row-store kernel,
    ops/pallas_scatter — a documented negative result kept for its
    platform findings).

    ``None`` (the default) consults the env once, when the builder runs —
    not inside the traced function, where jit caching (keyed on shapes
    only) would freeze the first value seen and make later env changes
    silently ineffective (round-2 advisor). MPI_GRID_LAND_SCATTER
    ∈ {overlay, xla, rows} picks explicitly; legacy
    MPI_GRID_PALLAS_SCATTER=1 still selects "rows". Passing an explicit
    value overrides the env entirely, so two settings can coexist in one
    process via two builders."""
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if scatter_impl is None:
        env = os.environ.get("MPI_GRID_LAND_SCATTER")
        if env is None and os.environ.get("MPI_GRID_PALLAS_SCATTER") == "1":
            env = "rows"
        impl = env or ("overlay" if on_tpu else "xla")
    elif scatter_impl is True:
        impl = "rows"
    elif scatter_impl is False:
        impl = "xla"
    else:
        impl = str(scatter_impl)
    if impl not in ("overlay", "xla", "rows"):
        raise ValueError(f"unknown landing-scatter impl {impl!r}")
    return impl if on_tpu else "xla"


def _land_scatter(flat, targets, cols, impl: str = "xla"):
    """The landing column-scatter on planar ``[K, m]`` state.

    ``impl`` is resolved by the builder via :func:`_resolve_scatter_impl`,
    never read from the env here. ``"overlay"`` is the planar one-hot
    overlay kernel (sort arrivals by target, stream the state through
    VMEM, place via MXU one-hot matmuls — no per-element stores; it
    falls back to the XLA scatter itself when its contract doesn't
    hold). ``"rows"`` is the round-2 per-row-store kernel, kept for its
    measured negative result; it takes row-major buffers, so that branch
    pays two transposes on top of its already-losing per-row stores.

    UNIQUENESS INVARIANT (the overlay kernel's correctness contract — a
    duplicate in-range target would accumulate two one-hot contributions
    into the half-planes and produce garbage words silently, where the
    XLA scatter merely picks one writer): every in-range ``targets``
    entry this module passes is unique by construction. In
    :func:`_land_arrivals` / the vranks ``land_plan``, targets are drawn
    from (a) ``vacated`` — distinct resident columns, because they come
    from disjoint prefixes of a PERMUTATION (``_plan_rows`` over the
    sort order), and (b) popped free-stack entries — distinct stack
    positions of a stack holding distinct column ids; (a) targets hold
    live rows and (b) targets hold holes, so the two sets are disjoint,
    and everything else is the drop sentinel. Callers introducing a new
    path into the overlay must preserve this."""
    if impl == "overlay":
        from mpi_grid_redistribute_tpu.ops import pallas_overlay

        return pallas_overlay.overlay_scatter_planar(flat, targets, cols)
    if impl == "rows":
        if flat.dtype != jnp.float32:
            # The row-store kernel is float32-only, and its per-row VMEM
            # stores are exactly the float copy chains that flush denormal
            # bit patterns — running it on a bitcast view of the int32
            # transport would reintroduce the round-4 corruption. Fail
            # loudly rather than silently measuring the XLA scatter under
            # the "rows" label.
            raise TypeError(
                "scatter_impl='rows' (MPI_GRID_LAND_SCATTER=rows) is "
                "float32-only and incompatible with the int32 bit-exact "
                "transport the migrate engines now carry; use 'overlay' "
                "or 'xla'"
            )
        from mpi_grid_redistribute_tpu.ops import pallas_scatter

        return pallas_scatter.scatter_rows(flat.T, targets, cols.T).T
    return flat.at[:, targets].set(cols, mode="drop")


def _pos_row(flat: jax.Array, d: int) -> jax.Array:
    """float32 VIEW of position row ``d`` of the fused state.

    The fused transport matrix is int32 (bit-pattern-safe on TPU vector
    units — see :func:`fuse_fields`); binning arithmetic needs the float
    values, so position rows are bitcast back here. Legacy float32 state
    passes through untouched."""
    row = flat[d, :]
    if row.dtype == jnp.int32:
        return lax.bitcast_convert_type(row, jnp.float32)
    return row


class MigrateStats(NamedTuple):
    """Per-step migration observability (SURVEY.md §5.5). Global shapes [R]
    (one entry per rank; with vranks, device-major ``dev * V + vrank``
    order). ``backlog`` counts migrants delayed by per-pair send capacity
    or by receiver grants (they stay resident and retry — never lost);
    ``dropped_recv`` remains as a surfaced safety counter for arrivals a
    receiver could not land, structurally zero now that sends are
    receiver-granted.

    ``flow`` is the per-pair FLOW MATRIX (telemetry/flow.py): global
    ``[R, R]`` int32, entry ``[i, j]`` = rows rank ``i`` sent to rank
    ``j`` this step. It is the granted send-count table both engines
    already compute for the pack phase, stacked into the stats pytree —
    zero extra device work, zero host syncs. Row sums equal ``sent``
    and column sums equal ``received`` exactly (sends are
    receiver-granted, so the two sides agree by construction). Defaults
    to ``None`` (an empty pytree leaf) for hand-built fixtures.

    ``fast_path`` (ISSUE 4) reports the mover-sparse engine's per-step
    branch decision: [V] int32 per shard, 1 = the step ran the O(movers)
    fast branch, 0 = the residence/overflow guard routed it to the dense
    engine. ``None`` (the default, and what every non-sparse engine
    emits) means the engine carries no sparse path at all — telemetry
    distinguishes "no fast path built" from "built but fell back". The
    step's mover count is derivable as ``sent + backlog``."""

    sent: jax.Array
    received: jax.Array
    population: jax.Array
    backlog: jax.Array
    dropped_recv: jax.Array  # structurally 0 since receiver-granted sends
    flow: jax.Array = None  # [R, R] granted sends; None in old fixtures
    fast_path: jax.Array = None  # [V] 1/0 sparse-branch taken; None = n/a


class InflightExchange(NamedTuple):
    """Everything the ISSUE half of a split migrate step hands the
    COMPLETE half (ISSUE 12 two-phase surface): the exchanged arrival
    pool plus the granted-count tables and the sender's vacated-slot
    plan. Carrying it across a scan iteration is what lets a
    software-pipelined macro-step overlap the exchange with the next
    step's drift/binning before the landing consumes it.

    ``recv`` is the planar ``[K, n_src * C]`` arrival pool (post-wire);
    ``backlog`` counts granted-short rows that stayed resident."""

    recv: jax.Array
    recv_counts: jax.Array
    send_counts: jax.Array
    gather_idx: jax.Array
    backlog: jax.Array


class MigrateState(NamedTuple):
    """Scan-carry state for the fused migration loop.

    ``fused`` is PLANAR ``[K, n]`` int32 (``[K, V * n]`` with V vranks —
    vrank ``v`` owns lane columns ``[v * n, (v + 1) * n)``): position
    component rows first (float32 values bitcast; view via
    :func:`_pos_row`), payload rows, and the alive row last (1/0).
    Legacy float32 state is still accepted by the engines, but only the
    int32 transport is bit-exact for arbitrary payload patterns on TPU
    (see :func:`fuse_fields`).
    ``free_stack`` / ``n_free`` are the hole-slot stack (indices of dead
    columns; only the first ``n_free`` entries are live), per vrank
    (``[V, n]`` / ``[V]``) on the vrank path."""

    fused: jax.Array
    free_stack: jax.Array
    n_free: jax.Array


def fuse_fields(arrays: Sequence[jax.Array], alive: jax.Array):
    """Pack [n, ...] arrays + alive mask into one PLANAR [K, n] INT32
    matrix (components on the sublane axis — see module docstring).

    32-bit dtypes are bitcast to int32 — the INTEGER transport is what
    makes "bit patterns survive exactly" TRUE ON HARDWARE: TPU float
    vector chains (fused gather/select/concat passes over f32 state)
    flush denormal f32 bit patterns — any bitcast int below 2^23 — to
    zero (measured on-chip in round 4: a bitcast-int32 id row came back
    all zeros through the f32 drift loop), while integer lanes have no
    FTZ semantics. The engines bitcast position rows back to float32
    views only where binning arithmetic needs values. The alive mask
    becomes the last row (1/0).

    Returns ``(fused, specs)``; ``specs`` drives :func:`unfuse_fields`.
    """
    n = arrays[0].shape[0]
    parts, specs = [], []
    for a in arrays:
        if a.dtype.itemsize != 4:
            raise TypeError(
                f"fused migration payload requires 32-bit dtypes, got "
                f"{a.dtype}; cast or split the field"
            )
        flat = a.reshape(n, -1)
        if flat.dtype != jnp.int32:
            flat = lax.bitcast_convert_type(flat, jnp.int32)
        parts.append(flat.T)
        specs.append((a.shape[1:], a.dtype))
    parts.append(alive.astype(jnp.int32)[None, :])
    return jnp.concatenate(parts, axis=0), tuple(specs)


def unfuse_fields(fused: jax.Array, specs):
    """Inverse of :func:`fuse_fields`: ``(arrays..., alive)``. Accepts the
    int32 transport layout (canonical) or the legacy float32 layout."""
    out = []
    row = 0
    n = fused.shape[1]
    for shape, dtype in specs:
        k = 1
        for s in shape:
            k *= s
        flat = fused[row : row + k, :].T
        if dtype != flat.dtype:
            flat = lax.bitcast_convert_type(flat, dtype)
        out.append(flat.reshape((n,) + tuple(shape)))
        row += k
    alive = fused[-1, :] > 0
    return tuple(out), alive


def init_state(
    fused: jax.Array, vranks: int = 1, batched: bool = None
) -> MigrateState:
    """Build the free-slot stack from the fused matrix's alive row.

    One-time cost (a full argsort) at loop entry; the stack is maintained
    incrementally afterwards. ``fused`` is planar ``[K, m]``; with
    ``vranks=V``, ``m = V * n`` and the stack is per-vrank ``[V, n]`` over
    LOCAL column indices. ``batched`` (default ``vranks > 1``) forces the
    per-vrank ``[V, n]`` / ``[V]`` stack shapes even at ``V = 1`` — the
    vranks engine (:func:`shard_migrate_vranks_fn`) always expects the
    batched form, while the flat engine expects scalars.
    """
    if batched is None:
        batched = vranks > 1
    alive = fused[-1, :] > 0  # alive row is exactly 0/1 in either dtype
    if batched:
        alive = alive.reshape(vranks, -1)

    def one(a):
        stack = jnp.argsort(
            jnp.where(a, jnp.int32(1), jnp.int32(0)), stable=True
        ).astype(jnp.int32)
        return stack, jnp.sum((~a).astype(jnp.int32))

    if batched:
        free_stack, n_free = jax.vmap(one)(alive)
    else:
        free_stack, n_free = one(alive)
    return MigrateState(fused, free_stack, n_free)


def _segment_of(k: jax.Array, cum: jax.Array) -> jax.Array:
    """For output position(s) ``k`` (any shape, k >= 0), the segment index
    under exclusive cumulative counts ``cum`` ([n_segs+1], cum[0]=0): the
    d with cum[d] <= k < cum[d+1]. Comparison-count against the cum
    table — ``jnp.searchsorted``'s default TPU lowering is a sequential
    per-query scan (measured 200+ ms at 5M queries; the fix bought the
    headline 52 -> 45 ms/step). Use only for cum tables that stay small
    (O(V)); for tables scaling with total rank count prefer
    :func:`_segment_of_auto`."""
    k = jnp.asarray(k)
    return jnp.sum(
        cum[(None,) * k.ndim + (slice(1, None),)] <= k[..., None],
        axis=-1,
        dtype=jnp.int32,
    )


def _segment_of_auto(k: jax.Array, cum: jax.Array) -> jax.Array:
    """:func:`_segment_of`, but switching to the merge-sort ``searchsorted``
    lowering once the cum table outgrows O(tens) entries — the
    comparison-count does O(n_segs) work per query, which on tables that
    scale with the total rank count (R+1, Dev*V+1) becomes O(R^2 * C) per
    step (round-2 advisor). Identical semantics on duplicate boundaries
    (empty segments resolve past the run of duplicates) and for
    ``k >= cum[-1]`` (returns n_segs)."""
    if cum.shape[0] <= 129:
        # comparison-count: O(n_segs) VECTORIZED work per query — cheap up
        # to O(128) tables. The merge-sort searchsorted below introduces a
        # sort op that XLA can neither slice through nor hoist; the
        # round-4 north-star knockout charged +56 ms to the vmapped
        # method="sort" lowering at V=64 (65-entry tables) where the
        # comparison-count costs ~100M vectorized compares (~2-4 ms).
        return _segment_of(k, cum)
    return (
        jnp.searchsorted(cum, k, side="right", method="sort").astype(
            jnp.int32
        )
        - 1
    )


def _cycle_rescue(pending, sends_zero, ok=None):
    """Force one self-financed swap along each stalled rotation cycle.

    The receiver-granted flow control has one liveness hole (round-2
    verdict item 5): a pure rotation cycle of length >= 3 between
    COMPLETELY full shards at zero free slots — pairwise swaps are zero
    and there are no free slots to grant, so the least fixpoint of the
    self-financing grant recursion is zero and the cycle backlogs forever.
    This helper detects such cycles and forces exactly ONE granted row on
    each cycle edge: every member then has one forced departure AND one
    forced arrival, so the arrival lands in the slot the member's own
    departure vacates — lossless with zero free slots, draining the cycle
    at one row per member per step.

    Args:
      pending: [S, S] int32, >0 where source s still wants to send to d
        after normal grants.
      sends_zero: [S] bool — source granted NOTHING this step (totally
        stalled). Only such sources participate (anything else is making
        progress already).
      ok: optional [S] bool budget guard; a cycle is applied only if ALL
        its members are ok (atomicity keeps the swap self-financed — a
        partially applied cycle would give some member an arrival with no
        departure).

    Returns [S, S] int32 in {0, 1}: the forced extra grants. Cycles are
    found in the functional graph v -> first pending destination of v,
    restricted to stalled sources, via log-squared boolean closure of the
    [S, S] adjacency — O(S^2 log S) elementwise work on tiny matrices.
    """
    S = pending.shape[0]
    has = jnp.any(pending > 0, axis=1) & sends_zero
    succ = jnp.argmax(pending > 0, axis=1)
    A = jnp.where(
        has[:, None], jax.nn.one_hot(succ, S, dtype=jnp.float32), 0.0
    )
    clo = A + jnp.eye(S, dtype=jnp.float32)
    for _ in range(max(1, (max(S, 2) - 1).bit_length())):
        clo = jnp.minimum(clo @ clo, 1.0)
    # v is on a cycle iff a path v -> succ(v) ->* v exists
    on_cycle = jnp.sum(A * clo.T, axis=1) > 0
    if ok is not None:
        # mutual reachability = the member set of v's cycle (functional
        # graphs have only cycle SCCs); drop cycles with any !ok member
        mutual = (clo * clo.T) > 0
        cycle_bad = jnp.any(mutual & ~ok[None, :], axis=1)
        on_cycle = on_cycle & ~cycle_bad
    return (A * on_cycle[:, None]).astype(jnp.int32)


def _stack_push_pop(free_stack, n_free, n_pop, n_push, vacated, n_in):
    """Free-stack update after landing: pops lower the head; net-excess
    vacated slots ``vacated[n_in : n_in + n_push]`` are pushed, via a
    read-modify-write of one contiguous window (never a scatter).

    ``vacated`` has static length P; the window is ``min(P, n)`` entries
    whose start is clamped in bounds. Returns ``(free_stack, n_free)``.

    Used by the vmapped vranks landing only: :func:`_land_arrivals` (and
    the two-phase landing it feeds, ISSUE 12) now inlines the equivalent
    full-width where-blend into the landing kernel itself, sharing the
    plan quantities the scatter already materialized.
    """
    n = free_stack.shape[0]
    P = vacated.shape[0]
    W = min(P, n)
    new_n_free = n_free - n_pop + n_push
    win_start = jnp.clip(n_free, 0, max(n - W, 0)).astype(jnp.int32)
    window = lax.dynamic_slice(free_stack, (win_start,), (W,))
    rel = n_free - win_start  # stack head position inside the window
    w_idx = jnp.arange(W, dtype=jnp.int32)
    # affine index (w + n_in - rel): one dynamic slice of the padded
    # plan replaces a [W]-element gather (out-of-use entries read the
    # zero pads and are masked below)
    buf = jnp.concatenate(
        [
            jnp.zeros((W,), vacated.dtype),
            vacated,
            jnp.zeros((W,), vacated.dtype),
        ]
    )
    pushes = lax.dynamic_slice(buf, (n_in - rel + W,), (W,))
    window = jnp.where(
        (w_idx >= rel) & (w_idx < rel + n_push), pushes, window
    )
    free_stack = lax.dynamic_update_slice(free_stack, window, (win_start,))
    return free_stack, new_n_free


def _land_arrivals(
    fused,
    free_stack,
    n_free,
    recv,
    recv_counts,
    send_counts,
    gather_idx,
    capacity: int,
    scatter_impl: str = "xla",
):
    """Land compacted arrivals into vacated slots, then popped holes.

    ``recv`` is the planar ``[K, n_src * C]`` arrival pool (per-source
    slots, only the first ``recv_counts[s]`` of each source's ``C``
    valid); ``send_counts`` / ``gather_idx`` describe this shard's own
    sends, whose slots are being vacated. One scatter writes arrivals,
    hole markers and the alive row together. Returns
    ``(fused, free_stack, n_free, n_in, dropped_recv)``.
    """
    n = fused.shape[1]
    C = capacity
    n_dest = send_counts.shape[0]
    n_src = recv_counts.shape[0]
    P = max(n_src, n_dest) * C  # write-plan length
    n_sent = jnp.sum(send_counts).astype(jnp.int32)
    n_in = jnp.sum(recv_counts).astype(jnp.int32)

    cum_send = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(send_counts)]
    )
    cum_recv = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv_counts)]
    )
    k_idx = jnp.arange(P, dtype=jnp.int32)
    d_of_k = _segment_of_auto(k_idx, cum_send)
    vacated = gather_idx[
        jnp.clip(d_of_k * C + (k_idx - cum_send[d_of_k]), 0, n_dest * C - 1)
    ]  # first n_sent entries: vacated slot ids
    s_of_k = _segment_of_auto(k_idx, cum_recv)
    arrivals = jnp.take(
        recv,
        jnp.clip(s_of_k * C + (k_idx - cum_recv[s_of_k]), 0, n_src * C - 1),
        axis=1,
    )  # first n_in columns: real arrivals (alive row already 1)

    # Write plan for slot j in [P]:
    #   j < min(n_in, n_sent): arrival j -> vacated[j]
    #   n_sent <= j < n_in:    arrival j -> popped free slot
    #   n_in <= j < n_sent:    hole marker -> vacated[j]
    # Receiver overflow: arrivals beyond n_sent + n_free drop (counted).
    n_pop = jnp.clip(n_in - n_sent, 0, n_free)
    dropped_recv = jnp.maximum(n_in - n_sent - n_free, 0).astype(jnp.int32)
    pop_idx = jnp.clip(n_free - 1 - (k_idx - n_sent), 0, n - 1)
    target = jnp.where(
        k_idx < jnp.minimum(n_in, n_sent),
        vacated,
        jnp.where(
            (k_idx >= n_sent) & (k_idx < n_sent + n_pop),
            free_stack[pop_idx],
            jnp.where((k_idx >= n_in) & (k_idx < n_sent), vacated, n),
        ),
    )
    cols = jnp.where((k_idx < n_in)[None, :], arrivals, 0)
    # THE scatter: payload + alive flag + hole markers in one pass.
    fused = _land_scatter(fused, target, cols, scatter_impl)

    # Free-stack update FUSED into the landing kernel (ISSUE 12): net
    # excess departures (n_sent - n_in when positive) were written as
    # holes at vacated[n_in : n_sent]; push them with a full-width
    # where-blend over the SAME plan quantities the scatter just
    # consumed (k-window arithmetic, ``vacated``) instead of the old
    # separate :func:`_stack_push_pop` windowed read-modify-write pass —
    # one fewer dynamic_slice/dynamic_update_slice pair per step, and
    # XLA fuses the blend into the landing fusion. ``n_pop`` and
    # ``n_push`` are mutually exclusive (one is the positive part of
    # ``n_in - n_sent``, the other of its negation), so the push base
    # ``n_free - n_pop`` equals ``n_free`` whenever pushes exist —
    # bit-identical stack contents to the windowed update.
    n_push = jnp.maximum(n_sent - n_in, 0)
    base = n_free - n_pop
    s_idx = jnp.arange(n, dtype=jnp.int32)
    push_vals = vacated[jnp.clip(n_in + s_idx - base, 0, P - 1)]
    free_stack = jnp.where(
        (s_idx >= base) & (s_idx < base + n_push), push_vals, free_stack
    )
    new_n_free = base + n_push
    return fused, free_stack, new_n_free, n_in, dropped_recv


def shard_migrate_fused_fn(
    domain: Domain, grid: ProcessGrid, capacity: int, ndim: int = None,
    cycle_rescue: bool = True, scatter_impl=None,
):
    """Per-shard migration on planar fused state (runs under ``shard_map``).

    Signature of the returned fn:
      ``MigrateState -> (MigrateState, MigrateStats)``
    where ``state.fused`` is ``[K, n]`` with rows ``0:ndim`` the position
    (default ``domain.ndim``) and the last row the alive flag. Columns with
    alive 0 are holes whose contents are unspecified.

    ``cycle_rescue`` (default on, auto-disabled above 128 ranks) drains
    full-shard rotation cycles via :func:`_cycle_rescue`: one extra
    all_gather of an [R] pending vector per step, then a forced
    self-financed swap along each detected cycle.
    """
    R = grid.nranks
    axes = grid.axis_names
    C = capacity
    D = domain.ndim if ndim is None else ndim
    rescue = cycle_rescue and R <= 128
    if cycle_rescue and not rescue:
        # The liveness guarantee silently changing with scale is worse
        # than the O(R^2 log R) closure cost it avoids — tell the caller
        # (round-3 verdict weak item 5).
        import warnings

        warnings.warn(
            f"cycle_rescue disabled: {R} ranks > 128 (the all-gathered "
            f"[R, R] boolean-closure cost grows as R^2 log R). Full-shard "
            f"rotation cycles will backlog instead of draining — watch "
            f"utils.stats.detect_stall, or pass cycle_rescue=False to "
            f"silence this warning.",
            stacklevel=2,
        )
    impl = _resolve_scatter_impl(scatter_impl)

    def issue(state: MigrateState) -> InflightExchange:
        """ISSUE half (ISSUE 12): bin -> grant -> pack -> wire. Leaves
        the resident state untouched (sent rows stay in place until the
        landing vacates them), so a pipelined caller can keep computing
        on ``state`` while the returned exchange is in flight."""
        fused, free_stack, n_free = state
        K = fused.shape[0]
        me = lax.axis_index(axes).astype(jnp.int32)
        alive = fused[-1, :] > 0
        with traced_span("mig:bin"):
            # per-axis fused elementwise binning (no stacked [D, n]
            # intermediates; see the vranks path for the measurement)
            dest = jnp.zeros(fused.shape[1:], jnp.int32)
            for d in range(D):
                p = _pos_row(fused, d)
                lo = jnp.asarray(domain.lo[d], p.dtype)
                ext = jnp.asarray(domain.extent[d], p.dtype)
                if domain.periodic[d]:
                    # reciprocal-multiply wrap: bit-equal for pow2
                    # extents, 4x cheaper than the f32 division in
                    # jnp.remainder
                    p = lo + binning.remainder_fast(p - lo, domain.extent[d])
                    p = jnp.where(p >= lo + ext, lo, p)
                inv_w = jnp.asarray(grid.shape[d], p.dtype) / ext
                cell_d = jnp.clip(
                    jnp.floor((p - lo) * inv_w).astype(jnp.int32),
                    0,
                    grid.shape[d] - 1,
                )
                dest = dest + cell_d * jnp.int32(grid.strides[d])
            leaving = alive & (dest != me)
            # Sentinel R: holes and staying residents sort to the tail.
            dest_key = jnp.where(leaving, dest, R).astype(jnp.int32)

            # two-level leaver selection; the [1, n] batch shape reuses
            # the vrank engine's machinery (scalar-guard cond, see
            # binning). order is prefix-only: valid through the leaver
            # count, zero tail (see sorted_dest_counts_batched) — every
            # read below is masked or sliced at granted counts.
            o_b, c_b, b_b = binning.sorted_dest_counts_batched(
                dest_key[None], R
            )
            order, full_counts, bounds = o_b[0], c_b[0], b_b[0]
        desired = jnp.minimum(full_counts, C).astype(jnp.int32)

        # Receiver-side flow control (lossless receive): exchange DESIRED
        # counts, let each receiver grant what it can land, send only the
        # granted rows; the rest stay resident and retry (backlog).
        # Grant = pairwise swaps (self-financing: each swap arrival has a
        # matching departure vacating a slot — both sides compute the same
        # symmetric min) + a greedy share of the free slots. Arrivals are
        # then structurally <= swaps + n_free, so the landing never drops.
        recv_desired = lax.all_to_all(
            desired, axes, split_axis=0, concat_axis=0, tiled=True
        )
        swap = jnp.minimum(recv_desired, desired)
        resid = _greedy_alloc(
            (recv_desired - swap)[:, None],
            jnp.maximum(n_free, 0)[None],
        )[:, 0].astype(jnp.int32)
        grants = swap + resid  # what I allow each source to send me
        grants_back = lax.all_to_all(
            grants, axes, split_axis=0, concat_axis=0, tiled=True
        )
        send_counts = jnp.minimum(desired, grants_back)
        # actual arrivals == my grants: grants <= recv_desired by
        # construction (swap and resid are both bounded by it), and each
        # sender sends exactly what I granted it
        recv_counts = grants

        if rescue:
            # drain full-shard rotation cycles: gather everyone's pending
            # vector, find cycles in the first-pending-destination graph
            # among totally-stalled shards, and force one granted swap
            # per cycle edge. Safe without guards here: a stalled sender
            # has an all-zero send row (so +1 <= C), and my grant to a
            # stalled pred was 0 (so its recv slot +1 <= C); the forced
            # arrival lands in the forced departure's vacated slot.
            pend_all = lax.all_gather(
                desired - send_counts, axes
            ).reshape(R, R)
            sent_tot = lax.all_gather(
                jnp.sum(send_counts), axes
            ).reshape(R)
            F = _cycle_rescue(pend_all, sent_tot == 0)
            send_counts = send_counts + F[me]
            recv_counts = recv_counts + F[:, me]
        backlog = jnp.sum(full_counts - send_counts).astype(jnp.int32)

        with traced_span("mig:pack"):
            send, gather_idx = _pack_cols(
                fused, order, bounds, send_counts, R, C
            )
        with traced_span("mig:exchange"):
            recv = lax.all_to_all(
                send.reshape(K, R, C).transpose(1, 0, 2), axes,
                split_axis=0, concat_axis=0, tiled=True,
            )  # [R, K, C]
            recv = recv.transpose(1, 0, 2).reshape(K, R * C)
        return InflightExchange(
            recv, recv_counts, send_counts, gather_idx, backlog
        )

    def complete(state: MigrateState, inflight: InflightExchange):
        """COMPLETE half (ISSUE 12): land the exchanged rows (free-stack
        update fused into the landing kernel) and assemble stats."""
        fused, free_stack, n_free = state
        with traced_span("mig:unpack"):
            fused, free_stack, n_free, n_in, dropped_recv = _land_arrivals(
                fused, free_stack, n_free, inflight.recv,
                inflight.recv_counts, inflight.send_counts,
                inflight.gather_idx, C, impl,
            )
        population = jnp.sum((fused[-1, :] > 0).astype(jnp.int32))
        stats = MigrateStats(
            sent=jnp.sum(inflight.send_counts).astype(jnp.int32)[None],
            received=n_in[None],
            population=population[None],
            backlog=inflight.backlog[None],
            dropped_recv=dropped_recv[None],
            # granted sends, already computed for the pack phase: my row
            # of the global [R, R] flow matrix (shard axis 0 stacks rows)
            flow=inflight.send_counts[None],
        )
        return MigrateState(fused, free_stack, n_free), stats

    def fn(state: MigrateState):
        return complete(state, issue(state))

    # the split halves ARE the engine: fn is their recomposition (pure
    # code motion — identical eqn order, so J004 profiles are untouched),
    # and exchange.resolve_two_phase routes pipelined callers here
    fn.issue = issue
    fn.complete = complete
    return fn


def _greedy_alloc(desired: jax.Array, cap: jax.Array) -> jax.Array:
    """Allocate ``desired[s, w]`` units across sources ``s`` per column
    ``w``, greedily in source order, never exceeding ``cap[w]`` total.
    Deterministic; sources with lower index win under pressure (backlogged
    rows keep stable priority and retry next step)."""
    cum = jnp.cumsum(desired, axis=0)
    prev = cum - desired
    capb = cap[None, :]
    return jnp.clip(jnp.minimum(cum, capb) - jnp.minimum(prev, capb), 0)


class VrankPlan(NamedTuple):
    """One step's routing decision from :class:`VrankTwoPhase.issue`
    (ISSUE 12): the sender-side vacated-slot plan, the receiver-side
    arrival gather plan (GLOBAL column ids into the ``[K, V * n]``
    matrix), the granted/desired count tables and the per-source
    ``backlog`` (rows the flow control declined this step). Plans are
    ``n``-wide — wide enough that the flow-control grant is the ONLY
    clip, so ``backlog == 0`` means every leaver was granted."""

    vacated: jax.Array  # [V, n] local vacated slot ids (first n_sent)
    n_sent: jax.Array  # [V]
    arr_plan: jax.Array  # [V, n] global arrival source columns
    n_in: jax.Array  # [V]
    allowed: jax.Array  # [V, V] granted sends [src, dst]
    desired: jax.Array  # [V, V] pre-grant leaver counts [src, dst]
    backlog: jax.Array  # [V] per-source granted-short rows


class VrankTwoPhase(NamedTuple):
    """The two-phase (start/finish) exchange surface for a SINGLE-DEVICE
    vrank mesh (ISSUE 12), built by :func:`vrank_exchange_two_phase_fn`
    and routed to callers via ``exchange.resolve_two_phase``.

    ``bin_key`` computes the per-column destination key; ``issue`` turns
    a key into a :class:`VrankPlan` (routing sort + receiver-granted
    flow control + cycle rescue + both gather plans); ``land`` lands a
    gathered arrival payload in ONE scatter with the free-stack update
    fused in. The split is what a software-pipelined macro-step needs:
    the plan + payload gather for step k can sit in flight while step
    k+1's drift/binning is issued, and the landing consumes them a full
    iteration later."""

    bin_key: object
    issue: object
    land: object
    vranks: int
    n_local: int


def vrank_exchange_two_phase_fn(
    domain: Domain, vgrid: ProcessGrid, n_local: int, ndim: int = None,
    cycle_rescue: bool = True, scatter_impl=None,
) -> VrankTwoPhase:
    """Build the Dev==1 planar vranks two-phase exchange (ISSUE 12).

    All ``V = vgrid.nranks`` ranks live on one device as lane-axis
    blocks of a planar ``[K, V * n]`` matrix, so the "wire" is a pair of
    in-HBM gathers and the issue/complete halves can be separated by a
    whole scan iteration without any collective in flight. Semantics
    mirror :func:`shard_migrate_fused_fn` (receiver-granted flow
    control, cycle rescue, single landing scatter) with plan width
    ``n = n_local`` per vrank: nothing is ever clipped by the plan, so
    ``backlog`` is exactly the flow-control residue.

    The landing scatter preserves the uniqueness invariant of
    :func:`_land_scatter`: per vrank, targets are vacated slots (disjoint
    prefixes of a sort permutation) plus popped stack entries (distinct
    hole ids), globalized onto disjoint column blocks across vranks.

    Note the per-row ``take_along_axis`` gathers here are [V, n]-scale;
    fine on CPU meshes (where this engine is currently gated), but a
    chip session should linearize them like :func:`_plan_rows_batched`
    before lifting the CPU-only restriction.
    """
    V = vgrid.nranks
    n = int(n_local)
    D = domain.ndim if ndim is None else ndim
    rescue = cycle_rescue and V <= 128
    impl = _resolve_scatter_impl(scatter_impl)

    def bin_key(fused: jax.Array) -> jax.Array:
        """[K, V*n] planar matrix -> [V, n] destination-vrank key, with
        the sentinel ``V`` on stayers and holes (the only values
        :func:`..ops.binning.sorted_dest_counts_batched` counts are
        genuine leavers). Routing is the SAME
        :func:`..ops.binning.rank_of_position_planar` the canonical
        planar engines call, so a pipelined step homes every particle on
        exactly the vrank the sequential engine would."""
        m = fused.shape[1]
        alive = fused[-1, :] > 0
        me = (jnp.arange(m, dtype=jnp.int32) // n).astype(jnp.int32)
        pos_f = lax.bitcast_convert_type(fused[:D, :], jnp.float32)
        dest = binning.rank_of_position_planar(pos_f, domain, vgrid)
        key = jnp.where(alive & (dest != me), dest, V).astype(jnp.int32)
        return key.reshape(V, n)

    def issue(key: jax.Array, n_free: jax.Array) -> VrankPlan:
        """Routing sort + receiver-granted flow control + gather plans.
        Reads only the key and the free-slot counts — never the payload
        — so a pipelined caller can issue step k+1 against a matrix
        whose step-k arrivals are still in flight."""
        order, counts, bounds = binning.sorted_dest_counts_batched(key, V)
        desired = counts.astype(jnp.int32)  # [V, V] [src, dst]
        swap = jnp.minimum(desired, desired.T)
        resid = _greedy_alloc(
            desired - swap, jnp.maximum(n_free, 0)
        ).astype(jnp.int32)
        allowed = swap + resid
        if rescue:
            pending = desired - allowed
            F = _cycle_rescue(pending, jnp.sum(allowed, axis=1) == 0)
            allowed = allowed + F
        backlog = jnp.sum(desired - allowed, axis=1).astype(jnp.int32)
        vacated, n_sent = _plan_rows_batched(
            bounds[:, :-1], allowed, order, n
        )
        arr_plan, n_in = _plan_rows_batched(
            bounds[:, :-1].T, allowed.T, order, n,
            seg_rows=jnp.arange(V, dtype=jnp.int32),
        )
        return VrankPlan(
            vacated, n_sent.astype(jnp.int32), arr_plan,
            n_in.astype(jnp.int32), allowed, desired, backlog,
        )

    def land(fused, free_stack, n_free, arr, vacated, n_sent, n_in):
        """Land a gathered ``[K, V, n]`` arrival payload: ONE scatter
        writes payload + alive + hole markers for every vrank, and the
        free-stack update rides the same plan quantities as a fused
        full-width blend (no second pass over the landing rows).
        Row-count agnostic: callers may land an augmented matrix (extra
        key row) through the same kernel. Returns
        ``(fused, free_stack, n_free, dropped [V])``."""
        Kx = fused.shape[0]
        k_idx = jnp.arange(n, dtype=jnp.int32)[None, :]  # [1, n]
        ns = n_sent[:, None]
        ni = n_in[:, None]
        n_pop = jnp.clip(n_in - n_sent, 0, n_free)  # [V]
        dropped = jnp.maximum(n_in - n_sent - n_free, 0).astype(jnp.int32)
        pop_idx = jnp.clip(
            n_free[:, None] - 1 - (k_idx - ns), 0, n - 1
        )
        popped = jnp.take_along_axis(free_stack, pop_idx, axis=1)
        target = jnp.where(
            k_idx < jnp.minimum(ni, ns),
            vacated,
            jnp.where(
                (k_idx >= ns) & (k_idx < ns + n_pop[:, None]),
                popped,
                jnp.where((k_idx >= ni) & (k_idx < ns), vacated, n),
            ),
        )  # [V, n] local targets, sentinel n
        v_off = jnp.arange(V, dtype=jnp.int32)[:, None]
        gtarget = jnp.where(target >= n, V * n, v_off * n + target)
        cols = jnp.where((k_idx < ni)[None, :, :], arr, 0)
        fused = _land_scatter(
            fused, gtarget.reshape(-1), cols.reshape(Kx, V * n), impl
        )
        # free-stack update fused into the landing (see _land_arrivals)
        n_push = jnp.maximum(n_sent - n_in, 0)
        base = n_free - n_pop
        s_idx = jnp.arange(n, dtype=jnp.int32)[None, :]
        push_vals = jnp.take_along_axis(
            vacated,
            jnp.clip(ni + s_idx - base[:, None], 0, n - 1),
            axis=1,
        )
        free_stack = jnp.where(
            (s_idx >= base[:, None]) & (s_idx < (base + n_push)[:, None]),
            push_vals,
            free_stack,
        )
        return fused, free_stack, base + n_push, dropped

    return VrankTwoPhase(bin_key, issue, land, V, n)


def _plan_rows(seg_starts, seg_counts, order, length: int):
    """Expand per-segment (start-in-sorted-order, count) pairs into a flat
    row plan of static ``length``: entry ``j`` is the resident-slot index of
    the ``j``-th planned row (segments concatenated in segment order, the
    first ``count`` rows of each — prefix semantics). Entries ``j >= total``
    are clipped junk; callers mask by ``j < total``.

    All inputs are per-vrank 1-D: ``seg_starts``/``seg_counts`` [n_segs],
    ``order`` [n] (stable sort permutation). Pure searchsorted + gather on
    [length] vectors — cost scales with ``length``, not with n.
    """
    n = order.shape[0]
    cum = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(seg_counts).astype(jnp.int32)]
    )
    j = jnp.arange(length, dtype=jnp.int32)
    seg = jnp.clip(
        _segment_of_auto(j, cum),
        0,
        seg_counts.shape[0] - 1,
    )
    pos = seg_starts[seg] + (j - cum[seg])
    return order[jnp.clip(pos, 0, n - 1)], cum[-1]


def _plan_rows_batched(seg_starts, seg_counts, order, length: int,
                       seg_rows=None, row_stride: int = None):
    """Batched :func:`_plan_rows` over a leading vrank axis, with every
    gather LINEARIZED into one wide-minor ``jnp.take(..., axis=1)``.

    ``vmap(_plan_rows)`` lowers its ``order[pos]`` to a batched gather
    that costs ~33 ns/element on this stack (round-4 north-star knockout:
    +52.5 ms for a [64, 24537] plan), while the same elements through a
    flat ``[1, V*n]`` axis-1 take cost ~1 ns/element (the arrival
    gather's pattern, phase 5). Inputs: ``seg_starts``/``seg_counts``
    [V, S], ``order`` [V, n]; returns ``(vacated [V, length],
    totals [V])``.

    ``seg_rows`` ([S] int32, round 4 — arrival plans): maps each segment
    to the row of ``order`` it reads — segments of one plan row may live
    in *different* rows (dst ``w`` reads source ``s``'s sorted space at
    segment ``s -> w``). The row index telescopes through the same mask
    (values < S << 2^24, exact in f32) and combines with the local
    position in int32 — positions themselves never exceed n, so the f32
    exactness bound of the einsum is untouched. Returned entries are
    GLOBALIZED: ``seg_row * n + order[seg_row, pos]`` (the [V, length]
    ``row_g * n`` add is O(V*M); pre-globalizing ``order`` instead
    would materialize an O(V*n) temp per step). Default: plan row v
    reads ``order[v]``, values raw.

    ``row_stride`` (ISSUE 4 — the mover-sparse engine): the column
    stride used to GLOBALIZE returned entries in ``seg_rows`` mode.
    Defaults to ``order.shape[-1]``, which conflates two distinct
    widths: the width of ``order`` (indexing) and the width of the
    destination matrix the plan addresses (globalization). The sparse
    fast path plans over a compacted ``[V, B]`` mover block whose
    values index the full ``[K, V * n]`` resident matrix — there
    ``order`` is B wide but the stride must stay ``n``.
    """
    V, S = seg_counts.shape
    n = order.shape[-1]
    stride = n if row_stride is None else row_stride
    cum = jnp.concatenate(
        [
            jnp.zeros((V, 1), jnp.int32),
            jnp.cumsum(seg_counts, axis=1).astype(jnp.int32),
        ],
        axis=1,
    )  # [V, S+1]
    j = jnp.arange(length, dtype=jnp.int32)
    # TELESCOPED segment lookup: with mask[v, j, s] = (j >= cum[v, s+1]),
    # seg = sum_s mask, and any gather from a per-segment table telescopes
    # through the same mask — f[seg[j]] = f[0] + sum_s mask * (f[s+1] -
    # f[s]). One [V, length, S] masked reduction replaces the 65-entry
    # table takes, which cost ~6 ns/element on this stack (round-4
    # diagnostic: +19 ms for two takes at the 64-vrank north-star).
    # Values stay < n = 2^20 << 2^24, exact in f32.
    mask = (
        cum[:, None, 1:] <= j[None, :, None]
    ).astype(jnp.float32)  # [V, length, S]
    d_start = jnp.diff(
        jnp.concatenate(
            [seg_starts, seg_starts[:, -1:]], axis=1
        ).astype(jnp.float32),
        axis=1,
    )  # [V, S]: seg_starts[s+1] - seg_starts[s] (last diff 0 = clamp)
    d_cum = jnp.diff(cum[:, :-1].astype(jnp.float32), axis=1)
    d_cum = jnp.concatenate(
        [d_cum, jnp.zeros((V, 1), jnp.float32)], axis=1
    )  # [V, S]: cum[s+1] - cum[s], clamped at the last segment
    # HIGHEST precision: the default TPU matmul rounds operands to bf16
    # (8-bit mantissa) — diffs reach 2^20 and must multiply exactly
    starts_g = (
        seg_starts[:, :1].astype(jnp.float32)
        + jnp.einsum(
            "vjs,vs->vj", mask, d_start,
            precision=jax.lax.Precision.HIGHEST,
        )
    ).astype(jnp.int32)
    cum_g = (
        jnp.einsum(
            "vjs,vs->vj", mask, d_cum,
            precision=jax.lax.Precision.HIGHEST,
        )
    ).astype(jnp.int32)  # cum[:, 0] == 0
    pos = starts_g + (j[None, :] - cum_g)
    if seg_rows is not None:
        d_row = jnp.diff(
            jnp.concatenate(
                [seg_rows, seg_rows[-1:]]
            ).astype(jnp.float32)
        )  # [S]: seg_rows[s+1] - seg_rows[s] (last diff 0 = clamp)
        row_g = (
            jnp.asarray(seg_rows[0], jnp.float32)
            + jnp.einsum(
                "vjs,s->vj", mask, d_row,
                precision=jax.lax.Precision.HIGHEST,
            )
        ).astype(jnp.int32)  # [V, length]
        idx = row_g * n + jnp.clip(pos, 0, n - 1)
    else:
        v_off = jnp.arange(V, dtype=jnp.int32)[:, None]
        idx = v_off * n + jnp.clip(pos, 0, n - 1)
    # 1-D index vector: the fast axis-1 take lowering keys off flat
    # indices (2-D index arrays fall back to the ~33 ns/elem gather)
    vac = jnp.take(
        order.reshape(1, -1), idx.reshape(-1), axis=1
    ).reshape(V, length)
    if seg_rows is not None:
        vac = row_g * stride + vac
    return vac, cum[:, -1]


def balanced_assignment(cell_loads, n_ranks: int) -> tuple:
    """Static cell -> rank map equalizing per-rank load (host-side, LPT).

    ``cell_loads`` is the measured per-cell ownership histogram ([n_cells]
    row-major, e.g. ``np.bincount`` of cell ids); the classic
    longest-processing-time greedy assigns cells heaviest-first to the
    least-loaded rank, guaranteeing max-bin <= 4/3 optimal. Returns a
    hashable tuple for :func:`shard_migrate_vranks_fn`'s ``assignment``
    (pair it with the cell grid as ``cells``). Slabs can then be sized
    from ``max(bin loads)`` — near the MEAN cell load times
    ``n_cells / n_ranks`` instead of the hottest cell times the same,
    which is the whole point under imbalance.
    """
    import numpy as np

    loads = np.asarray(cell_loads, dtype=np.int64)
    if loads.ndim != 1 or loads.size < n_ranks:
        raise ValueError(
            f"need >= {n_ranks} cells, got shape {loads.shape}"
        )
    order = np.argsort(-loads, kind="stable")
    bins = np.zeros((n_ranks,), np.int64)
    assign = np.zeros(loads.shape, np.int32)
    for c in order:
        r = int(np.argmin(bins))
        assign[c] = r
        bins[r] += loads[c]
    return tuple(int(x) for x in assign)


def shard_migrate_vranks_fn(
    domain: Domain,
    dev_grid: ProcessGrid,
    vgrid: ProcessGrid,
    capacity: int,
    ndim: int = None,
    local_budget: int = None,
    scatter_impl=None,  # None | "overlay" | "xla" | "rows" | bool
    cycle_rescue: bool = True,
    cells: ProcessGrid = None,
    assignment: tuple = None,
    mover_cap: int = None,
):
    """Migration over a ``dev_grid * vgrid`` process grid, planar layout.

    The full Cartesian grid has shape ``dev_grid.shape * vgrid.shape``
    (elementwise): device cell ``i // v`` and vrank cell ``i % v`` per axis.
    Each device owns ``V = vgrid.nranks`` subdomain slabs, side by side on
    the lane axis of one planar ``[K, V * n]`` matrix (vrank ``v`` owns
    columns ``[v * n, (v + 1) * n)``).

    Two-tier exchange (the TPU answer to MPI ranks on fewer nodes):

    * **On-device vrank->vrank traffic never touches a padded collective
      layout.** Migrants are routed compactly: one stable sort groups them,
      [V, V] count matrices allocate arrivals, and a single gather + single
      scatter sized to ``local_budget`` columns move exactly the migrants
      (the round-1 design paid gather+scatter over the full ``R*C`` padded
      layout — ~80 ns/row over mostly-empty slots dominated the step).
      Local routing is **lossless**: senders see receiver free-slot counts
      directly (same device) and hold rows back (``backlog``) instead of
      ever dropping an arrival.
    * **Cross-device traffic** rides a ``[Dev, V_src, V_dst, K, C]``
      ``lax.all_to_all`` over ICI, ``capacity`` columns per (source vrank,
      destination vrank) pair, and is **receiver-granted**: desired counts
      fly first, each destination vrank greedily grants within its free
      slots, grants fly back, and only granted rows are packed — excess
      movers backlog instead of ever hitting a full receiver (the wire
      never carries what cannot land; ``dropped_recv`` stays a safety
      counter). Mutually-full rotation cycles — including cycles that
      span devices — are drained by the cycle rescue (one forced,
      stack-financed row per cycle edge per step; global pass up to 128
      global ranks). When ``Dev == 1`` the collectives and their
      buffers compile away entirely.

    Signature of the returned per-shard fn:
      ``MigrateState -> (MigrateState, MigrateStats)``
    with ``state.fused [K, V * n]``, ``free_stack [V, n]``, ``n_free [V]``;
    stats entries are ``[V]`` per device (global device-major order).
    ``local_budget`` bounds on-device migrants per (vrank, step) in each
    direction (default ``V * capacity``, matching the round-1 total) — the
    landing scatter's cost scales with this PLAN length, not with actual
    migrants, so size it to a few x the expected per-step migration;
    ``capacity`` bounds cross-device migrants per (source vrank,
    destination vrank) pair. ``scatter_impl`` selects the landing-scatter
    implementation: ``None`` (env / platform default — "overlay" on TPU),
    ``"overlay"`` (planar one-hot overlay kernel), ``"xla"``, or
    ``"rows"`` (round-2 per-row-store kernel, a kept negative result);
    bools are accepted for backward compatibility (True = "rows",
    False = "xla"). See :func:`_resolve_scatter_impl`.

    **Load-balanced assignment** (``cells`` + ``assignment``): by default a
    vrank IS a spatial subdomain of the ``dev_grid * vgrid`` product grid —
    under load imbalance every slab must then be sized for the hottest
    subdomain (9.4x slot waste at 7x imbalance, round-2 verdict). Passing
    ``cells`` (the spatial cell grid, e.g. 4x4x4) with ``assignment`` (a
    static tuple mapping row-major cell id -> global rank ``dev * V + v``,
    typically from :func:`balanced_assignment` over a measured ownership
    histogram) decouples storage from space: each vrank owns an arbitrary
    SET of cells with near-equal total load, so uniform static slabs sized
    ~mean load suffice. Only the binning changes (cell id -> one small
    table gather); all routing, flow control and landing below operate on
    rank ids and are untouched. This is the classic HPC answer to
    imbalance — balance the decomposition, not the buffers — in
    static-shape TPU form.

    **Mover-sparse fast path** (``mover_cap``, ISSUE 4): at ~2%
    migration the dense step still pays full-array sort/pack/landing
    over every resident row. Passing ``mover_cap`` (a static mover
    budget per vrank per step, e.g. ``local_budget``) builds a second
    engine behind ONE scalar ``lax.cond``: the two-level selection
    compacts the leavers into a dense ``[V, mover_cap]`` block
    (:func:`..ops.binning.sorted_mover_block`), the grant tables are
    computed on the [V, V] count matrices exactly as the dense engine
    does, and when the residence/overflow guard holds — selection exact,
    nothing clipped (zero backlog), movers and arrivals within
    ``mover_cap`` — landing gathers and scatters only mover columns
    while stayer rows are never touched. Guard-violating steps run the
    dense engine unchanged; outputs are bit-identical either way (the
    guard conditions make the dense plans collapse to the leaver prefix
    the block reproduces). Only built at ``Dev == 1`` (cross-device
    traffic is already mover-sparse and a cond'd collective would
    deadlock); with ``mover_cap`` set the stats carry a ``fast_path``
    [V] leaf (1 = fast branch taken) — ``None`` otherwise. Size
    ``mover_cap`` like ``local_budget`` and grow it with
    :class:`..api.MoverCapacity` on sustained fallbacks.
    """
    axes = dev_grid.axis_names
    V = vgrid.nranks
    Dev = dev_grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim
    M = V * C if local_budget is None else local_budget
    R_total = Dev * V
    if (cells is None) != (assignment is None):
        raise ValueError("cells and assignment must be passed together")
    if assignment is not None:
        if len(assignment) != cells.nranks:
            raise ValueError(
                f"assignment has {len(assignment)} entries for "
                f"{cells.nranks} cells"
            )
        bad = [g for g in assignment if not 0 <= g < R_total]
        if bad:
            raise ValueError(
                f"assignment targets outside [0, {R_total}): {bad[:4]}"
            )
        full_grid = cells
    else:
        full_shape = tuple(
            d * v for d, v in zip(dev_grid.shape, vgrid.shape)
        )
        full_grid = ProcessGrid(full_shape, axis_names=dev_grid.axis_names)
    # static plan lengths: most rows a vrank can send / receive in a step
    S_max = M + ((Dev - 1) * V * C if Dev > 1 else 0)
    P = max(M, S_max)
    if cycle_rescue and Dev > 1 and R_total > 128:
        # same degradation signal as the flat engine (round-3 weak item
        # 5): above 128 global ranks the GLOBAL cycle rescue is off
        # (R^2 log R closure) and only the per-device rescue remains —
        # cross-device rotation cycles backlog again.
        import warnings

        warnings.warn(
            f"global cycle_rescue disabled: {R_total} global ranks > 128 "
            f"(the all-gathered [R, R] boolean-closure cost grows as "
            f"R^2 log R). Per-device cycles still drain, but rotation "
            f"cycles SPANNING devices will backlog — watch "
            f"utils.stats.detect_stall, or pass cycle_rescue=False to "
            f"silence this warning.",
            stacklevel=2,
        )
    scatter_impl = _resolve_scatter_impl(scatter_impl)

    def fn(state: MigrateState, dest_key=None):
        flat, free_stack, n_free = state  # [K, V*n], [V, n], [V]
        K = flat.shape[0]
        n = flat.shape[1] // V
        me_dev = lax.axis_index(axes).astype(jnp.int32)
        my_v = jnp.arange(V, dtype=jnp.int32)  # vrank ids on this device

        # ---- binning: per-axis fused elementwise chains (no stacked
        # [D, m] intermediates — each axis's wrap+floor+clip+accumulate
        # fuses into one pass over [V*n]; the stacked helper variant
        # measured 22x its bandwidth roofline in the knockout profile).
        # A caller may pass a precomputed ``dest_key`` [V, n] instead
        # (device-major global rank, sentinel R_total for holes/stayers)
        # — the fused Pallas drift+wrap+bin kernel emits it in the same
        # streaming pass as the drift (ops/pallas_driftbin.py,
        # bit-identical to this chain by test).
        if dest_key is None:
            alive = flat[-1, :].reshape(V, n) > 0
            dest_dev = jnp.zeros((V * n,), jnp.int32)
            dest_v = jnp.zeros((V * n,), jnp.int32)
            for d in range(D):
                p = _pos_row(flat, d)
                lo = jnp.asarray(domain.lo[d], p.dtype)
                ext = jnp.asarray(domain.extent[d], p.dtype)
                if domain.periodic[d]:
                    # reciprocal-multiply wrap (see shard_migrate_fused_fn)
                    p = lo + binning.remainder_fast(
                        p - lo, domain.extent[d]
                    )
                    p = jnp.where(p >= lo + ext, lo, p)
                inv_w = jnp.asarray(full_grid.shape[d], p.dtype) / ext
                cell_d = jnp.clip(
                    jnp.floor((p - lo) * inv_w).astype(jnp.int32),
                    0,
                    full_grid.shape[d] - 1,
                )
                if assignment is not None:
                    # accumulate the full row-major cell id; ownership
                    # comes from the static assignment table below
                    dest_v = dest_v + cell_d * jnp.int32(
                        full_grid.strides[d]
                    )
                else:
                    vs = vgrid.shape[d]
                    if dev_grid.shape[d] == 1:
                        # single device slab on this axis: cell_d < vs
                        # statically, so the // and % are identities —
                        # int32 div/mod have no native VPU lowering and
                        # cost real passes over [V*n] (round-4 phase-1
                        # attribution)
                        dest_v = dest_v + cell_d * vgrid.strides[d]
                    else:
                        dest_dev = (
                            dest_dev + (cell_d // vs) * dev_grid.strides[d]
                        )
                        dest_v = dest_v + (cell_d % vs) * vgrid.strides[d]
            if assignment is not None:
                # one gather from the tiny [n_cells] table: cell ->
                # global rank
                g = jnp.take(
                    jnp.asarray(assignment, jnp.int32), dest_v, axis=0
                )
                dest_dev = g // V
                dest_v = g - dest_dev * V
            dest_dev = dest_dev.reshape(V, n)
            dest_v = dest_v.reshape(V, n)
            staying = (dest_dev == me_dev) & (dest_v == my_v[:, None])
            leaving = alive & ~staying
            # device-major global destination: dev * V + vrank
            dest_key = jnp.where(
                leaving, dest_dev * V + dest_v, R_total
            ).astype(jnp.int32)  # [V, n]

        def _step(flat, free_stack, n_free, dest_key):
            """One full DENSE redistribute step given a precomputed
            destination key — the planar vranks engine, O(residents)
            per step. Extracted as a closure so the mover-sparse fast
            path (dispatch below) can route guard-violating steps here
            through ONE scalar ``lax.cond``; without ``mover_cap`` it
            is simply called directly (status quo)."""
            # NOTE a flat composite-key sort (one [V*n] sort replacing the V
            # vmapped sorts) was measured and REJECTED: the vmapped
            # sorted_dest_counts is 5.7 ms at 8x1M while the flat composite
            # sort alone is 9.8 ms, and the boundary lookup it then needs —
            # searchsorted(method="sort"), 72 queries over 8.4M keys — costs
            # a pathological ~97 ms on this stack (scripts/microbench_sort.py).
            # ALSO REJECTED (late round 4): lax.top_k with k = plan capacity
            # on a packed descending key — the order below is only consumed
            # up to the first `leavers` entries, so a truncated selection
            # would suffice semantically, but top_k lowers 2-5.8x SLOWER
            # than the full packed sort (both packing in-loop: 14.6 vs
            # 2.5 ms at 8x1M, 111.2 vs 56.8 at 64x1M —
            # scripts/microbench_topk.py); a Pallas stream compaction was
            # sketched and dropped: within-chunk placement needs a [T, T]
            # one-hot whose VPU construction (~275G elem ops at 64M) dwarfs
            # the sort it would replace.
            # Two-level leaver selection (binning.sorted_dest_counts_batched):
            # chunk sorts + one small candidate sort reproduce the consumed
            # leaver prefix bit-for-bit at ~2.4x the flat packed sort's speed
            # (56.3 -> 23.6 ms at 64x1M, scripts/microbench_select.py); a
            # scalar guard cond-routes dense steps to the flat sort.
            # order is prefix-only (zero tail past the leavers; see
            # sorted_dest_counts_batched) — reads below slice/mask at counts.
            with traced_span("mig:bin"):
                order, counts, bounds = binning.sorted_dest_counts_batched(
                    dest_key, R_total
                )  # [V, n], [V, R_total], [V, R_total + 1]
            leavers = jnp.sum(counts, axis=1).astype(jnp.int32)  # [V]

            # ---- local allocation: [V_src, V_dst] on this device ----------
            loc0 = me_dev * V
            loc_counts = lax.dynamic_slice_in_dim(counts, loc0, V, axis=1)
            loc_starts = lax.dynamic_slice_in_dim(bounds, loc0, V, axis=1)
            # per-source budget M: prefix truncation in destination order
            # (rel = each pair segment's offset within the source's local run)
            rel_start = loc_starts - loc_starts[:, :1]
            rel_end = rel_start + loc_counts
            eff = jnp.clip(
                jnp.minimum(rel_end, M) - jnp.minimum(rel_start, M),
                0,
            ).astype(jnp.int32)

            # remote sends first: they vacate slots independently of the local
            # allocation, so they seed the receiver-capacity fixpoint. With
            # Dev > 1 the sends are RECEIVER-GRANTED (lossless receive): the
            # desired per-pair counts fly first, each destination vrank
            # greedily grants within its pre-step free slots, the grants fly
            # back, and only granted rows are packed — ungranted rows stay
            # resident and retry (backlog). Remote arrivals are then
            # structurally <= n_free and the remote landing never drops.
            # (Unlike the flat path there is no cross-device swap financing —
            # the remote landing pops free slots only — so mutually-full
            # vranks on different devices trade through backlog.)
            if Dev > 1:
                desired_rem = jnp.minimum(counts, C).astype(jnp.int32)
                g_ids = jnp.arange(R_total, dtype=jnp.int32)
                is_local_g = (g_ids >= loc0) & (g_ids < loc0 + V)
                desired_rem = jnp.where(
                    is_local_g[None, :], 0, desired_rem
                )  # [V_src, R_total]
                # desired -> receiver (same transpose layout as the payload)
                desired_t = desired_rem.reshape(V, Dev, V).transpose(1, 0, 2)
                recv_desired = lax.all_to_all(
                    desired_t, axes, split_axis=0, concat_axis=0, tiled=True
                ).transpose(2, 0, 1).reshape(V, Dev * V)  # [V_dst, S_global]
                grants = _greedy_alloc(
                    recv_desired.T, jnp.maximum(n_free, 0)
                ).T.astype(jnp.int32)  # [V_dst, S_global]
                # grants -> sender (reverse layout)
                grants_t = grants.reshape(V, Dev, V).transpose(1, 0, 2)
                grants_back = lax.all_to_all(
                    grants_t, axes, split_axis=0, concat_axis=0, tiled=True
                ).transpose(2, 0, 1).reshape(V, Dev * V)  # [V_src, G_dst]
                rem_sent_full = jnp.minimum(desired_rem, grants_back)
                sent_remote = jnp.sum(rem_sent_full, axis=1).astype(jnp.int32)
                # actual arrivals == my grants (greedy allocates within each
                # source's desire, so grants <= recv_desired always)
                recv_counts_rem = grants
                n_in_rem = jnp.sum(recv_counts_rem, axis=1).astype(jnp.int32)
            else:
                sent_remote = jnp.zeros((V,), jnp.int32)
                n_in_rem = jnp.zeros((V,), jnp.int32)

            # Receiver capacity: arrivals may use current free slots PLUS slots
            # vacated by the receiver's own sends this step — otherwise
            # fully-occupied vranks that need to swap livelock. Sends depend on
            # destination capacities (circular), so solve by monotone-increasing
            # fixpoint, seeded with pairwise swaps (which are self-financing:
            # each vrank's swap arrivals exactly equal its swap departures).
            # Every truncation of the increasing orbit is safe: iteration t's
            # arrivals <= n_free + sends(t-1) + remote <= n_free + actual sends.
            swap = jnp.minimum(eff, eff.T).astype(jnp.int32)
            # trim so swap arrivals fit the [M] arrival plan per dst, then
            # re-symmetrize (min with transpose keeps column sums <= M and
            # restores the self-financing arrivals == departures invariant)
            swap = _greedy_alloc(
                swap, jnp.full((V,), M, jnp.int32)
            ).astype(jnp.int32)
            swap = jnp.minimum(swap, swap.T)
            res_eff = eff - swap
            res = jnp.zeros_like(eff)
            # free slots already promised to granted remote arrivals are off
            # the table for local arrivals (remote lands after local and only
            # pops the stack)
            n_free_local = n_free - n_in_rem
            for _ in range(V):
                cap_res = jnp.minimum(
                    M - jnp.sum(swap, axis=0),
                    n_free_local + sent_remote + jnp.sum(res, axis=1),
                ).astype(jnp.int32)
                res = _greedy_alloc(res_eff, jnp.maximum(cap_res, 0)).astype(
                    jnp.int32
                )
            allowed = swap + res  # [V_src, V_dst]
            if cycle_rescue and (Dev == 1 or R_total > 128):
                # drain full-vrank rotation cycles on THIS device (all the
                # tables are local — no collective needed). A cycle is only
                # forced if every member stays within the [M] arrival/send
                # plans (+1 row); partial application would break the
                # self-financing pairing, so the guard is per whole cycle.
                # (Above 128 global ranks the global pass below is off —
                # matching the flat engine's R^2 log R closure bound — and
                # this per-device rescue is the remaining guarantee.)
                pending_loc = (res_eff - res).astype(jnp.int32)
                sends_zero = (
                    jnp.sum(allowed, axis=1) + sent_remote
                ) == 0
                ok = (jnp.sum(allowed, axis=1) < M) & (
                    jnp.sum(allowed, axis=0) < M
                )
                allowed = allowed + _cycle_rescue(
                    pending_loc, sends_zero, ok
                )
            elif cycle_rescue:
                # GLOBAL rescue (round-3 verdict item 6): a rotation cycle
                # that SPANS devices has no swap financing in the grant
                # phase (remote grants draw on free slots only), so at zero
                # free slots it backlogs under the normal protocol. Gather
                # the full pending matrix, run the same functional-graph
                # closure the flat engine uses, and force one row per cycle
                # edge. The forced arrivals are financed by the forced
                # departures through the EXISTING landing machinery: a
                # member's forced remote departure vacates a slot that the
                # local landing phase pushes onto the free stack
                # (n_push = n_sent - n_in_local), and the remote landing —
                # which runs after — pops exactly that slot; local-edge
                # forced arrivals land in the vacated-slot plan directly.
                # Every tier stays lossless at zero holes.
                pending_loc = (res_eff - res).astype(jnp.int32)
                pending_rows = desired_rem - rem_sent_full  # local cols are 0
                pending_rows = lax.dynamic_update_slice(
                    pending_rows, pending_loc, (jnp.int32(0), loc0)
                )  # [V, R_total]
                sent_loc_v = jnp.sum(allowed, axis=1).astype(jnp.int32)
                recv_loc_v = jnp.sum(allowed, axis=0).astype(jnp.int32)

                def gat(x):
                    return lax.all_gather(x, axes).reshape(
                        (R_total,) + x.shape[1:]
                    )

                pending_g = gat(pending_rows)  # [R_total, R_total]
                sends_zero_g = gat(sent_loc_v + sent_remote) == 0
                sent_loc_g = gat(sent_loc_v)
                recv_loc_g = gat(recv_loc_v)
                rem_sent_g = gat(rem_sent_full)  # [R_total, R_total]
                g_all = jnp.arange(R_total, dtype=jnp.int32)
                succ_g = jnp.argmax(pending_g > 0, axis=1)
                same_dev = (succ_g // V) == (g_all // V)
                # per-member guard on ITS forced edge (v -> succ(v)); every
                # cycle edge is thus checked via its sender. Local edge:
                # sender's local-send plan AND receiver's [M] arrival plan
                # have room. Remote edge: the (v, succ) pair buffer has a
                # free slot (covers both ends; the arrival pops the slot the
                # departure pushes).
                ok_g = jnp.where(
                    same_dev,
                    (sent_loc_g < M) & (recv_loc_g[succ_g] < M),
                    rem_sent_g[g_all, succ_g] < C,
                )
                F = _cycle_rescue(pending_g, sends_zero_g, ok_g)
                F_rows = lax.dynamic_slice(
                    F, (loc0, jnp.int32(0)), (V, R_total)
                )  # my vranks' forced sends
                F_loc = lax.dynamic_slice(F_rows, (jnp.int32(0), loc0), (V, V))
                allowed = allowed + F_loc
                is_local_g2 = (g_all >= loc0) & (g_all < loc0 + V)
                F_rem = jnp.where(is_local_g2[None, :], 0, F_rows)
                rem_sent_full = rem_sent_full + F_rem
                sent_remote = jnp.sum(rem_sent_full, axis=1).astype(jnp.int32)
                F_cols = lax.dynamic_slice(
                    F, (jnp.int32(0), loc0), (R_total, V)
                )  # forced arrivals into my vranks, by global source
                F_cols_rem = jnp.where(is_local_g2[:, None], 0, F_cols)
                recv_counts_rem = recv_counts_rem + F_cols_rem.T
                n_in_rem = jnp.sum(recv_counts_rem, axis=1).astype(jnp.int32)
            sent_local = jnp.sum(allowed, axis=1).astype(jnp.int32)
            n_in_local = jnp.sum(allowed, axis=0).astype(jnp.int32)

            # ---- remote sends: [Dev, V_src, V_dst, K, C] over ICI ---------
            if Dev > 1:
                # build the send buffer by index arithmetic + one flat column
                # gather; global rank ids enumerate dev-major (columns
                # 0..R_total-1 of the count/bound tables)
                c_i = jnp.arange(C, dtype=jnp.int32)
                cnt_sg = rem_sent_full  # [V_src, R_total]
                start_sg = bounds[:, :R_total]
                valid = c_i[None, None, :] < cnt_sg[:, :, None]
                pos = start_sg[:, :, None] + c_i[None, None, :]
                # flat 1-D take (same ~33 ns/elem batched-gather avoidance
                # as the plan paths; take_along_axis with 2-D indices falls
                # back to the slow lowering)
                row = jnp.take(
                    order.reshape(1, -1),
                    (
                        my_v[:, None] * n
                        + jnp.clip(pos, 0, n - 1).reshape(V, -1)
                    ).reshape(-1),
                    axis=1,
                ).reshape(V, Dev * V, C)
                gsrc = my_v[:, None, None] * n + row
                vals = jnp.take(flat, gsrc.reshape(-1), axis=1).reshape(
                    K, V, Dev, V, C
                )
                send = jnp.where(
                    valid.reshape(V, Dev, V, C)[None], vals, 0
                )
                # [K, V_src, Dev, V_dst, C] -> [Dev, V_src, V_dst, K, C]
                send = send.transpose(2, 1, 3, 0, 4)
                with traced_span("mig:exchange"):
                    recv = lax.all_to_all(
                        send, axes, split_axis=0, concat_axis=0, tiled=True
                    )  # [Dev_src, V_src, V_dst, K, C]
                    # per-dst pools: [V_dst, K, Dev_src * V_src * C]; arrival
                    # counts (recv_counts_rem) were derived locally in the
                    # grant phase — no extra counts exchange needed
                    recv = recv.transpose(2, 3, 0, 1, 4).reshape(
                        V, K, Dev * V * C
                    )

            n_sent = sent_local + sent_remote

            # ---- vacated slots: all columns leaving each vrank ------------
            # segments: V local pairs (prefix `allowed`) then, with Dev > 1,
            # R_total global ranks (remote prefix `rem_sent_full`).
            if Dev > 1:
                seg_starts = jnp.concatenate(
                    [loc_starts, bounds[:, :R_total]], axis=1
                )
                seg_counts = jnp.concatenate([allowed, rem_sent_full], axis=1)
                vacated, _tot = _plan_rows_batched(
                    seg_starts, seg_counts, order, P
                )  # [V, P] (linearized — vmapped gathers cost ~33 ns/elem)
            elif P <= n:
                # UNCLIPPED fast path (single-device): stayers sort to the
                # END (sentinel key R_total), so leavers are a PREFIX of
                # sorted space grouped by dest, and `eff`'s budget cap is a
                # prefix truncation — when the grant phase clips nothing
                # (allowed == eff, the steady-state common case) the slow
                # plan's positions reduce to pos[v, j] = j exactly, i.e.
                # vacated IS order[:, :P]. The telescoped-einsum plan + its
                # ~19 ns/element order[pos] take (round-4 north-star
                # knockout: +30 ms, the phase-4 floor) collapse to one
                # slice. Entries beyond sum(allowed) differ between the
                # branches but are never read (every consumer masks at
                # k < n_sent). Clipped steps take the exact slow path.
                if os.environ.get("MPI_GRID_VACATED_PLAN") == "slow":
                    # diagnostic escape hatch (trace-time): force the general
                    # plan to measure what the fast path saves in context
                    vacated = _plan_rows_batched(
                        loc_starts, allowed, order, P
                    )[0]
                else:
                    unclipped = jnp.all(allowed == eff)
                    vacated = lax.cond(
                        unclipped,
                        lambda: lax.slice_in_dim(order, 0, P, axis=1),
                        lambda: _plan_rows_batched(
                            loc_starts, allowed, order, P
                        )[0],
                    )
            else:
                vacated, _tot = _plan_rows_batched(
                    loc_starts, allowed, order, P
                )

            # ---- local arrivals: one column gather sized to the budget ----
            # dst w's arrivals: sources in order, first allowed[s, w] rows of
            # each (s -> w) segment; arrival columns are globally indexed so
            # one flat gather serves every vrank.
            # dst w's plan walks SOURCE s's sorted space at segment (s -> w):
            # same telescoped/flat-take machinery as the vacated plan
            # (seg_rows maps segment s to order row s and globalizes the
            # result to s * n + row; the vmapped `order[s, pos]` form this
            # replaces pays the ~33 ns/element batched-gather toll — the
            # round-4 knockout hid it inside the in-context landing phase).
            with traced_span("mig:pack"):
                arr_src, _ = _plan_rows_batched(
                    loc_starts.T, allowed.T, order, M,
                    seg_rows=jnp.arange(V, dtype=jnp.int32),
                )  # [V_dst, M] global source columns
                arr_cols = _gather_plan_cols(flat, arr_src)  # [K, V, M]

            # ---- landing plan: one flat scatter for arrivals + holes ------
            k_idx = jnp.arange(P, dtype=jnp.int32)

            def land_plan(vac, nin, nsent, nf):
                n_pop = jnp.clip(nin - nsent, 0, nf)
                pop_idx = jnp.clip(nf - 1 - (k_idx - nsent), 0, n - 1)
                target = jnp.where(
                    k_idx < jnp.minimum(nin, nsent),
                    vac,
                    jnp.where(
                        (k_idx >= nsent) & (k_idx < nsent + n_pop),
                        jnp.zeros((), jnp.int32),  # replaced below (stack)
                        jnp.where(
                            (k_idx >= nin) & (k_idx < nsent), vac, n
                        ),
                    ),
                )
                return target, n_pop, pop_idx

            targets, n_pop, pop_idx = jax.vmap(land_plan)(
                vacated, n_in_local, n_sent, n_free
            )
            # The pop positions are an AFFINE sequence (stack head downward:
            # nf-1, nf-2, ... for k in [nsent, nsent+n_pop)), so the gather
            # is really a reversed contiguous window: slice it, reverse it,
            # and shift it into k-alignment with one more dynamic slice —
            # [P]-sized copies instead of a V*P-element random gather.
            W2 = min(P, n)  # window length (P can exceed n in tiny tests)

            def pops_window(fs_v, nf, nsent):
                start = jnp.clip(nf - W2, 0, n - W2)
                win_rev = lax.dynamic_slice(fs_v, (start,), (W2,))[::-1]
                # win_rev[i] = fs_v[start + W2 - 1 - i]; want
                # pops[k] = fs_v[nf - 1 - (k - nsent)] = win_rev[k + s],
                # s = start + W2 - nf - nsent  (every in-use k lands inside
                # the window; out-of-use entries read the zero pads and are
                # masked by use_pop below)
                s = start + W2 - nf - nsent
                buf = jnp.concatenate(
                    [
                        jnp.zeros((P,), fs_v.dtype),
                        win_rev,
                        jnp.zeros((P,), fs_v.dtype),
                    ]
                )
                return lax.dynamic_slice(buf, (s + P,), (P,))

            pops = jax.vmap(pops_window)(free_stack, n_free, n_sent)
            use_pop = (k_idx[None, :] >= n_sent[:, None]) & (
                k_idx[None, :] < (n_sent + n_pop)[:, None]
            )
            targets = jnp.where(use_pop, pops, targets)
            # global column ids; sentinel n -> out of range of [V*n] (dropped)
            gtargets = jnp.where(
                targets >= n, V * n, my_v[:, None] * n + targets
            )
            cols_w = jnp.zeros((K, V, P), flat.dtype).at[:, :, :M].set(
                arr_cols
            )
            cols_w = jnp.where(
                (k_idx[None, :] < n_in_local[:, None])[None], cols_w, 0
            )
            with traced_span("mig:unpack"):
                flat = _land_scatter(
                    flat, gtargets.reshape(-1), cols_w.reshape(K, V * P),
                    scatter_impl,
                )

            # ---- free-stack update (contiguous window blend) --------------
            n_push = jnp.maximum(n_sent - n_in_local, 0)
            free_stack, n_free = jax.vmap(_stack_push_pop)(
                free_stack, n_free, n_pop, n_push, vacated, n_in_local
            )

            # ---- remote landing: pops only, overflow counted --------------
            if Dev > 1:
                P_rem = Dev * V * C
                kr = jnp.arange(P_rem, dtype=jnp.int32)

                def land_remote(f, fs, nf, pool, rcnt):
                    # f [K, n] (one vrank's columns), pool [K, P_rem]
                    cum = jnp.concatenate(
                        [jnp.zeros((1,), jnp.int32), jnp.cumsum(rcnt)]
                    ).astype(jnp.int32)
                    nin = cum[-1]
                    # cum here has Dev*V + 1 entries (scales with the whole
                    # machine): use the auto helper (merge-sort searchsorted
                    # beyond O(tens) segments)
                    s = jnp.clip(
                        _segment_of_auto(kr, cum), 0, Dev * V - 1
                    )
                    src_slot = jnp.clip(
                        s * C + (kr - cum[s]), 0, P_rem - 1
                    )
                    arrivals = jnp.take(pool, src_slot, axis=1)
                    npop = jnp.minimum(nin, nf)
                    dropped = (nin - npop).astype(jnp.int32)
                    pop_i = jnp.clip(nf - 1 - kr, 0, n - 1)
                    tgt = jnp.where(kr < npop, fs[pop_i], n)
                    f = f.at[:, tgt].set(
                        jnp.where((kr < nin)[None, :], arrivals, 0),
                        mode="drop",
                    )
                    return f, nf - npop, nin, dropped

                flat3, n_free, n_in_rem, dropped_recv = jax.vmap(
                    land_remote,
                    in_axes=(1, 0, 0, 0, 0),
                    out_axes=(1, 0, 0, 0),
                )(flat.reshape(K, V, n), free_stack, n_free, recv,
                  recv_counts_rem)
                flat = flat3.reshape(K, V * n)
                received = n_in_local + n_in_rem
            else:
                dropped_recv = jnp.zeros((V,), jnp.int32)
                received = n_in_local

            backlog = (leavers - n_sent).astype(jnp.int32)
            population = jnp.sum(
                (flat[-1, :].reshape(V, n) > 0).astype(jnp.int32), axis=1
            )
            # my V rows of the global [R_total, R_total] flow matrix: remote
            # granted sends with the local block overlaid (both tables are
            # already live for the pack phase — pure stacking, no collective,
            # no host sync). With Dev == 1 the local table IS the full matrix.
            if Dev > 1:
                flow_rows = lax.dynamic_update_slice(
                    rem_sent_full, allowed, (jnp.int32(0), loc0)
                )  # [V, R_total]
            else:
                flow_rows = allowed
            stats = MigrateStats(
                sent=n_sent,
                received=received,
                population=population,
                backlog=backlog,
                dropped_recv=dropped_recv,
                flow=flow_rows,
            )
            return MigrateState(flat, free_stack, n_free), stats

        # ---- engine dispatch: mover-sparse fast path (ISSUE 4) --------
        # Built only when the caller passes ``mover_cap`` AND the whole
        # grid lives on one device: cross-device traffic already rides a
        # mover-sparse C-padded all_to_all, and a cond'd collective
        # would deadlock unless every device took the same branch.
        # Static infeasibility (selection cannot shrink the problem,
        # packing overflow, MPI_GRID_SELECT=flat) also runs dense — with
        # a [V] zeros ``fast_path`` leaf so the stats pytree is uniform
        # for a given call signature.
        B = None
        if mover_cap is not None and Dev == 1:
            B = max(1, min(int(mover_cap), n))
            sel_chunk, sel_cap = binning.sparse_select_params(n, B)
            if not binning.sparse_select_feasible(
                n, R_total, chunk=sel_chunk, cap=sel_cap
            ):
                B = None
        if B is None:
            out_state, stats = _step(flat, free_stack, n_free, dest_key)
            if mover_cap is not None:
                stats = stats._replace(
                    fast_path=jnp.zeros((V,), jnp.int32)
                )
            return out_state, stats

        # ---- shared sparse prefix: O(movers) selection + grant tables -
        # The two-level selection compacts the leavers into a dense
        # [V, B] mover block (exact iff no chunk overflows sel_cap —
        # the ``ok_sel`` scalar); the [V, V] grant fixpoint below is the
        # verbatim dense-engine allocation (Dev == 1 terms only), so
        # under the guard ``allowed_s`` IS the dense engine's ``allowed``.
        with traced_span("mig:select"):
            block_rows, s_counts, s_bounds, ok_sel = (
                binning.sorted_mover_block(
                    dest_key, R_total, B, chunk=sel_chunk, cap=sel_cap
                )
            )  # [V, B], [V, V], [V, V + 1] (R_total == V at Dev == 1)
        loc_counts = s_counts
        loc_starts = s_bounds[:, :V]
        rel_start = loc_starts - loc_starts[:, :1]
        rel_end = rel_start + loc_counts
        eff = jnp.clip(
            jnp.minimum(rel_end, M) - jnp.minimum(rel_start, M), 0
        ).astype(jnp.int32)
        swap = jnp.minimum(eff, eff.T).astype(jnp.int32)
        swap = _greedy_alloc(
            swap, jnp.full((V,), M, jnp.int32)
        ).astype(jnp.int32)
        swap = jnp.minimum(swap, swap.T)
        res_eff = eff - swap
        res = jnp.zeros_like(eff)
        for _ in range(V):
            cap_res = jnp.minimum(
                M - jnp.sum(swap, axis=0),
                n_free + jnp.sum(res, axis=1),
            ).astype(jnp.int32)
            res = _greedy_alloc(res_eff, jnp.maximum(cap_res, 0)).astype(
                jnp.int32
            )
        allowed_s = (swap + res).astype(jnp.int32)
        n_sent_s = jnp.sum(allowed_s, axis=1).astype(jnp.int32)
        n_in_s = jnp.sum(allowed_s, axis=0).astype(jnp.int32)
        # Residence/overflow guard, ONE scalar (a vmapped cond would
        # lower to a select and run both branches):
        #   * ok_sel — the mover block holds every leaver, exactly;
        #   * allowed_s == loc_counts — nothing was clipped by budget,
        #     free slots, or grants. Since allowed <= eff <= counts
        #     elementwise, equality means eff == counts too, the dense
        #     cycle rescue's pending matrix is zero (it would add
        #     nothing) and backlog is structurally zero;
        #   * arrivals fit the [B] landing plan.
        guard = (
            ok_sel
            & jnp.all(allowed_s == loc_counts)
            & jnp.all(n_in_s <= B)
        )

        # gridlint: fastpath-engine
        def _fast_branch():
            # O(movers) landing: the mover block IS the vacated-slot
            # plan (under the guard the dense engine's unclipped vacated
            # plan is exactly the leaver prefix of sorted order, which
            # the block reproduces bit-for-bit), arrivals gather B
            # columns, one targeted scatter writes B columns per vrank,
            # and the ~98% stayer columns are never touched — no
            # full-array permutation, no overlay landing.
            k_b = jnp.arange(B, dtype=jnp.int32)
            with traced_span("mig:pack"):
                arr_src, _ = _plan_rows_batched(
                    loc_starts.T, allowed_s.T, block_rows, B,
                    seg_rows=jnp.arange(V, dtype=jnp.int32),
                    row_stride=n,
                )  # [V_dst, B] global source columns
                arr_cols = _gather_plan_cols(flat, arr_src)  # [K, V, B]

            def land_plan(vac, nin, nsent, nf):
                n_pop = jnp.clip(nin - nsent, 0, nf)
                target = jnp.where(
                    k_b < jnp.minimum(nin, nsent),
                    vac,
                    jnp.where(
                        (k_b >= nsent) & (k_b < nsent + n_pop),
                        jnp.zeros((), jnp.int32),  # replaced below
                        jnp.where(
                            (k_b >= nin) & (k_b < nsent), vac, n
                        ),
                    ),
                )
                return target, n_pop

            targets, n_pop = jax.vmap(land_plan)(
                block_rows, n_in_s, n_sent_s, n_free
            )
            Wb = min(B, n)

            def pops_window(fs_v, nf, nsent):
                start = jnp.clip(nf - Wb, 0, n - Wb)
                win_rev = lax.dynamic_slice(fs_v, (start,), (Wb,))[::-1]
                s = start + Wb - nf - nsent
                buf = jnp.concatenate(
                    [
                        jnp.zeros((B,), fs_v.dtype),
                        win_rev,
                        jnp.zeros((B,), fs_v.dtype),
                    ]
                )
                return lax.dynamic_slice(buf, (s + B,), (B,))

            pops = jax.vmap(pops_window)(free_stack, n_free, n_sent_s)
            use_pop = (k_b[None, :] >= n_sent_s[:, None]) & (
                k_b[None, :] < (n_sent_s + n_pop)[:, None]
            )
            targets = jnp.where(use_pop, pops, targets)
            gtargets = jnp.where(
                targets >= n, V * n, my_v[:, None] * n + targets
            )
            cols_w = jnp.where(
                (k_b[None, :] < n_in_s[:, None])[None], arr_cols, 0
            )
            with traced_span("mig:unpack"):
                # always the targeted XLA scatter: the overlay kernel's
                # one-hot matmul is O(n * plan) — exactly the
                # O(residents) landing cost this branch exists to avoid
                new_flat = _land_scatter(
                    flat, gtargets.reshape(-1),
                    cols_w.reshape(K, V * B), "xla",
                )
            n_push = jnp.maximum(n_sent_s - n_in_s, 0)
            new_stack, new_free = jax.vmap(_stack_push_pop)(
                free_stack, n_free, n_pop, n_push, block_rows, n_in_s
            )
            stats = MigrateStats(
                sent=n_sent_s,
                received=n_in_s,
                # stack invariant: population == n - n_free (init_state
                # builds the stack from the alive row; every landing
                # preserves it) — an O(V) read where the dense engine
                # pays an O(n) alive-row reduce
                population=(n - new_free).astype(jnp.int32),
                backlog=jnp.zeros((V,), jnp.int32),
                dropped_recv=jnp.zeros((V,), jnp.int32),
                flow=allowed_s,
            )
            return MigrateState(new_flat, new_stack, new_free), stats

        # the dense fallback goes through a lambda, not a bare function
        # reference: _step's Dev > 1 collectives are statically absent
        # here (Dev == 1), and the lambda keeps gridlint's G001
        # cond-branch scan (lexical by design) out of the dense body
        out_state, stats = lax.cond(
            guard,
            _fast_branch,
            lambda: _step(flat, free_stack, n_free, dest_key),
        )
        return out_state, stats._replace(
            fast_path=jnp.broadcast_to(guard.astype(jnp.int32), (V,))
        )

    return fn


def shard_migrate_fn(domain: Domain, grid: ProcessGrid, capacity: int):
    """Per-field wrapper over the fused path (runs under ``shard_map``).

    Signature of the returned fn:
      ``(pos[n,D], alive[n] bool, *fields) ->
        (pos, alive, *fields, MigrateStats)``
    with identical shapes; rows where ``alive`` is False are holes. Fields
    must have 32-bit dtypes (see :func:`fuse_fields`); loops should carry
    :class:`MigrateState` across steps instead (see
    ``models.nbody.make_migrate_loop``) to skip the per-step fuse/unfuse and
    free-stack rebuild.
    """
    fused_fn = shard_migrate_fused_fn(domain, grid, capacity)

    def fn(pos, alive, *fields):
        fused, specs = fuse_fields((pos,) + tuple(fields), alive)
        state, stats = fused_fn(init_state(fused))
        out, alive_new = unfuse_fields(state.fused, specs)
        return (out[0], alive_new) + tuple(out[1:]) + (stats,)

    return fn
