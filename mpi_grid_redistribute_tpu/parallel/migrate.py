"""Resident-state migration: the fast drift-loop exchange (SURVEY.md §3.3).

The general :mod:`exchange` path re-packs every particle into canonical MPI
``Alltoallv`` receive order each step — full-array gathers plus a pool-wide
stable sort. Profiling on the real chip shows the true TPU cost model:

  * random-access scatter costs ~85 ns *per row* regardless of row width
    (a [4M,6] scatter of 256k rows is ~22 ms) — scatters must be few and
    sized to the data actually moved;
  * ``segment_sum`` histograms lower to scatter-add (~37 ms at 4M) — counts
    must come from ``searchsorted`` on already-sorted keys instead;
  * a full stable sort of 4M int32 keys is ~6 ms; elementwise binning ~3 ms.

Design (one compiled step, all static shapes):

  1. bin -> ``leaving`` mask (alive rows whose owner changed);
  2. ONE stable key sort groups leaving rows by destination; per-destination
     counts fall out of ``searchsorted`` on the sorted keys (no scatter-add);
  3. migrants beyond the per-(source,dest) ``capacity`` — or beyond what
     the receiver GRANTS (below) — simply STAY resident and retry next
     step (surfaced as ``backlog``; particles are never dropped);
  4. receiver-side flow control makes the receive lossless: desired
     per-pair counts fly first, each receiver grants pairwise swaps
     (self-financing: a swap arrival's matching departure vacates a slot)
     plus a greedy share of its free slots, grants fly back, and only
     granted rows are packed — arrivals are structurally bounded by what
     can land;
  5. one fused ``[R, C, K]`` ``lax.all_to_all`` moves position + payload +
     alive column as a single float32 matrix (32-bit fields bitcast);
  6. arrivals land exactly in the slots vacated by departures, then in slots
     popped from a carried free-slot *stack* (contiguous dynamic-slice
     push/pop — never a scatter); one single scatter per step writes
     payload, alive flag, and vacancy markers together; ``dropped_recv``
     remains as a surfaced safety counter and is structurally zero.

Known limit of the granted scheme (both paths): a pure rotation cycle of
length >= 3 between COMPLETELY full shards at exactly zero free slots
stalls in ``backlog`` — pairwise swaps are zero and there are no free
slots to grant. Any hole anywhere on the cycle drains it. Size slabs
with headroom (every bench/demo uses fill <= 0.9); the stall is visible
(a constant nonzero ``backlog``), never silent loss.

**Virtual ranks** (:func:`shard_migrate_vranks_fn`): each device can host a
whole sub-grid of subdomains ("vranks", vmapped slabs), so a 4x4x4 grid runs
on 8 chips — or on one — with identical semantics: the per-vrank pack/land
phases vmap, and the cross-device hop is one ``lax.all_to_all`` on the
``[D, V_src, V_dst, C, K]`` buffer; vrank-to-vrank traffic on the same
device never leaves HBM. This is the TPU answer to running an R-rank MPI
job on fewer nodes (SURVEY.md §2 process-grid topology, §7.6 scale).

Slot order is *not* the MPI canonical order — arrivals fill arbitrary holes.
Correctness is therefore set-equality per shard against the oracle (tested),
not bit-equality; use :mod:`exchange` when canonical order matters.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning


def _land_scatter(flat, targets, rows):
    """The landing row-scatter; switchable to the Pallas streamed-overlay
    kernel (ops/pallas_scatter) via MPI_GRID_PALLAS_SCATTER=1 on TPU.
    Read at trace time."""
    if os.environ.get("MPI_GRID_PALLAS_SCATTER") == "1" and (
        jax.devices()[0].platform in ("tpu", "axon")
    ):
        from mpi_grid_redistribute_tpu.ops import pallas_scatter

        return pallas_scatter.scatter_rows(flat, targets, rows)
    return flat.at[targets].set(rows, mode="drop")


class MigrateStats(NamedTuple):
    """Per-step migration observability (SURVEY.md §5.5). Global shapes [R]
    (one entry per rank; with vranks, device-major ``dev * V + vrank``
    order). ``backlog`` counts migrants delayed by per-pair send capacity
    or by receiver grants (they stay resident and retry — never lost);
    ``dropped_recv`` remains as a surfaced safety counter for arrivals a
    receiver could not land, structurally zero now that sends are
    receiver-granted."""

    sent: jax.Array
    received: jax.Array
    population: jax.Array
    backlog: jax.Array
    dropped_recv: jax.Array  # structurally 0 since receiver-granted sends


class MigrateState(NamedTuple):
    """Scan-carry state for the fused migration loop.

    ``fused`` is ``[n, K]`` float32 (``[V, n, K]`` with vranks): position
    columns, payload columns, and an alive column last. ``free_stack`` /
    ``n_free`` are the hole-slot stack (indices of dead rows; only the first
    ``n_free`` entries are live)."""

    fused: jax.Array
    free_stack: jax.Array
    n_free: jax.Array


def fuse_fields(arrays: Sequence[jax.Array], alive: jax.Array):
    """Pack [n, ...] arrays + alive mask into one [n, K] float32 matrix.

    32-bit dtypes are bitcast; the fused matrix only ever moves bytes
    (gather/scatter/all_to_all), so bit patterns survive exactly. The alive
    mask becomes the last column (1.0/0.0).

    Returns ``(fused, specs)``; ``specs`` drives :func:`unfuse_fields`.
    """
    n = arrays[0].shape[0]
    parts, specs = [], []
    for a in arrays:
        if a.dtype.itemsize != 4:
            raise TypeError(
                f"fused migration payload requires 32-bit dtypes, got "
                f"{a.dtype}; cast or split the field"
            )
        flat = a.reshape(n, -1)
        if flat.dtype != jnp.float32:
            flat = lax.bitcast_convert_type(flat, jnp.float32)
        parts.append(flat)
        specs.append((a.shape[1:], a.dtype))
    parts.append(alive.astype(jnp.float32)[:, None])
    return jnp.concatenate(parts, axis=1), tuple(specs)


def unfuse_fields(fused: jax.Array, specs):
    """Inverse of :func:`fuse_fields`: ``(arrays..., alive)``."""
    out = []
    col = 0
    n = fused.shape[0]
    for shape, dtype in specs:
        k = 1
        for s in shape:
            k *= s
        flat = fused[:, col : col + k]
        if dtype != jnp.float32:
            flat = lax.bitcast_convert_type(flat, dtype)
        out.append(flat.reshape((n,) + tuple(shape)))
        col += k
    alive = fused[:, -1] > 0.5
    return tuple(out), alive


def init_state(fused: jax.Array) -> MigrateState:
    """Build the free-slot stack from the fused matrix's alive column.

    One-time cost (a full argsort) at loop entry; the stack is maintained
    incrementally afterwards. Works on ``[n, K]`` or vmapped ``[V, n, K]``.
    """
    if fused.ndim == 3:
        states = jax.vmap(init_state)(fused)
        return states
    alive = fused[:, -1] > 0.5
    # dead slots first, ascending slot order
    free_stack = jnp.argsort(
        jnp.where(alive, jnp.int32(1), jnp.int32(0)), stable=True
    ).astype(jnp.int32)
    n_free = jnp.sum((~alive).astype(jnp.int32))
    return MigrateState(fused, free_stack, n_free)


def _segment_of(k: jax.Array, cum: jax.Array) -> jax.Array:
    """For output position(s) ``k`` (any shape, k >= 0), the segment index
    under exclusive cumulative counts ``cum`` ([n_segs+1], cum[0]=0): the
    d with cum[d] <= k < cum[d+1]. Comparison-count against the cum
    table — ``jnp.searchsorted``'s default TPU lowering is a sequential
    per-query scan (measured 200+ ms at 5M queries; the fix bought the
    headline 52 -> 45 ms/step). Use only for cum tables that stay small
    (O(V)); for tables scaling with total rank count prefer
    ``jnp.searchsorted(..., method="sort")``."""
    k = jnp.asarray(k)
    return jnp.sum(
        cum[(None,) * k.ndim + (slice(1, None),)] <= k[..., None],
        axis=-1,
        dtype=jnp.int32,
    )


def _pack_rows(fused, order, bounds, send_counts, n_dest: int,
               capacity: int):
    """Gather the first ``send_counts[d]`` sorted rows of each destination
    segment into a ``[n_dest * C, K]`` send pool (zero in invalid slots).
    Returns ``(send, gather_idx)``; ``gather_idx[j]`` is the resident row
    feeding send slot ``j`` (unique over valid slots)."""
    n = fused.shape[0]
    C = capacity
    c_idx = jnp.arange(C, dtype=jnp.int32)
    flat_c = jnp.tile(c_idx, n_dest)
    flat_d = jnp.repeat(jnp.arange(n_dest, dtype=jnp.int32), C)
    slot_valid = flat_c < send_counts[flat_d]
    src = jnp.minimum(bounds[flat_d] + flat_c, n - 1)
    gather_idx = order[src]  # [n_dest*C] unique over valid slots
    send = jnp.where(
        slot_valid[:, None], jnp.take(fused, gather_idx, axis=0), 0.0
    )
    return send, gather_idx


def _stack_push_pop(free_stack, n_free, n_pop, n_push, vacated, n_in):
    """Free-stack update after landing: pops lower the head; net-excess
    vacated slots ``vacated[n_in : n_in + n_push]`` are pushed, via a
    read-modify-write of one contiguous window (never a scatter).

    ``vacated`` has static length P; the window is ``min(P, n)`` entries
    whose start is clamped in bounds. Returns ``(free_stack, n_free)``.
    """
    n = free_stack.shape[0]
    P = vacated.shape[0]
    W = min(P, n)
    new_n_free = n_free - n_pop + n_push
    win_start = jnp.clip(n_free, 0, max(n - W, 0)).astype(jnp.int32)
    window = lax.dynamic_slice(free_stack, (win_start,), (W,))
    rel = n_free - win_start  # stack head position inside the window
    w_idx = jnp.arange(W, dtype=jnp.int32)
    pushes = vacated[jnp.clip(n_in + (w_idx - rel), 0, P - 1)]
    window = jnp.where(
        (w_idx >= rel) & (w_idx < rel + n_push), pushes, window
    )
    free_stack = lax.dynamic_update_slice(free_stack, window, (win_start,))
    return free_stack, new_n_free


def _land_arrivals(
    fused,
    free_stack,
    n_free,
    recv,
    recv_counts,
    send_counts,
    gather_idx,
    capacity: int,
):
    """Land compacted arrivals into vacated slots, then popped holes.

    ``recv`` is the flat ``[n_src * C, K]`` arrival pool (per-source slots,
    only the first ``recv_counts[s]`` of each source's ``C`` valid);
    ``send_counts`` / ``gather_idx`` describe this shard's own sends, whose
    slots are being vacated. One scatter writes arrivals, hole markers and
    the alive column together. Returns
    ``(fused, free_stack, n_free, n_in, dropped_recv)``.
    """
    n = fused.shape[0]
    C = capacity
    n_dest = send_counts.shape[0]
    n_src = recv_counts.shape[0]
    P = max(n_src, n_dest) * C  # write-plan length
    n_sent = jnp.sum(send_counts).astype(jnp.int32)
    n_in = jnp.sum(recv_counts).astype(jnp.int32)

    cum_send = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(send_counts)]
    )
    cum_recv = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv_counts)]
    )
    k_idx = jnp.arange(P, dtype=jnp.int32)
    d_of_k = _segment_of(k_idx, cum_send)
    vacated = gather_idx[
        jnp.clip(d_of_k * C + (k_idx - cum_send[d_of_k]), 0, n_dest * C - 1)
    ]  # first n_sent entries: vacated slot ids
    s_of_k = _segment_of(k_idx, cum_recv)
    arrivals = jnp.take(
        recv,
        jnp.clip(s_of_k * C + (k_idx - cum_recv[s_of_k]), 0, n_src * C - 1),
        axis=0,
    )  # first n_in rows: real arrivals (alive column already 1)

    # Write plan for slot j in [P]:
    #   j < min(n_in, n_sent): arrival j -> vacated[j]
    #   n_sent <= j < n_in:    arrival j -> popped free slot
    #   n_in <= j < n_sent:    hole marker -> vacated[j]
    # Receiver overflow: arrivals beyond n_sent + n_free drop (counted).
    n_pop = jnp.clip(n_in - n_sent, 0, n_free)
    dropped_recv = jnp.maximum(n_in - n_sent - n_free, 0).astype(jnp.int32)
    pop_idx = jnp.clip(n_free - 1 - (k_idx - n_sent), 0, n - 1)
    target = jnp.where(
        k_idx < jnp.minimum(n_in, n_sent),
        vacated,
        jnp.where(
            (k_idx >= n_sent) & (k_idx < n_sent + n_pop),
            free_stack[pop_idx],
            jnp.where((k_idx >= n_in) & (k_idx < n_sent), vacated, n),
        ),
    )
    rows = jnp.where((k_idx < n_in)[:, None], arrivals, 0.0)
    # THE scatter: payload + alive flag + hole markers in one pass.
    fused = fused.at[target].set(rows, mode="drop")

    # Free-stack update: net excess departures (n_sent - n_in when
    # positive) were written as holes at vacated[n_in : n_sent]: push them.
    n_push = jnp.maximum(n_sent - n_in, 0)
    free_stack, new_n_free = _stack_push_pop(
        free_stack, n_free, n_pop, n_push, vacated, n_in
    )
    return fused, free_stack, new_n_free, n_in, dropped_recv


def shard_migrate_fused_fn(
    domain: Domain, grid: ProcessGrid, capacity: int, ndim: int = None
):
    """Per-shard migration on fused state (runs under ``shard_map``).

    Signature of the returned fn:
      ``MigrateState -> (MigrateState, MigrateStats)``
    where ``state.fused`` is ``[n, K]`` with columns ``0:ndim`` the position
    (default ``domain.ndim``) and the last column the alive flag. Rows with
    alive 0 are holes whose contents are unspecified.
    """
    R = grid.nranks
    axes = grid.axis_names
    C = capacity
    D = domain.ndim if ndim is None else ndim

    def fn(state: MigrateState):
        fused, free_stack, n_free = state
        K = fused.shape[1]
        me = lax.axis_index(axes).astype(jnp.int32)
        alive = fused[:, -1] > 0.5
        dest = binning.rank_of_position(fused[:, :D], domain, grid)
        leaving = alive & (dest != me)
        # Sentinel R: holes and staying residents sort to the tail.
        dest_key = jnp.where(leaving, dest, R).astype(jnp.int32)

        order, full_counts, bounds = binning.sorted_dest_counts(dest_key, R)
        desired = jnp.minimum(full_counts, C).astype(jnp.int32)

        # Receiver-side flow control (lossless receive): exchange DESIRED
        # counts, let each receiver grant what it can land, send only the
        # granted rows; the rest stay resident and retry (backlog).
        # Grant = pairwise swaps (self-financing: each swap arrival has a
        # matching departure vacating a slot — both sides compute the same
        # symmetric min) + a greedy share of the free slots. Arrivals are
        # then structurally <= swaps + n_free, so the landing never drops.
        recv_desired = lax.all_to_all(
            desired, axes, split_axis=0, concat_axis=0, tiled=True
        )
        swap = jnp.minimum(recv_desired, desired)
        resid = _greedy_alloc(
            (recv_desired - swap)[:, None],
            jnp.maximum(n_free, 0)[None],
        )[:, 0].astype(jnp.int32)
        grants = swap + resid  # what I allow each source to send me
        grants_back = lax.all_to_all(
            grants, axes, split_axis=0, concat_axis=0, tiled=True
        )
        send_counts = jnp.minimum(desired, grants_back)
        backlog = jnp.sum(full_counts - send_counts).astype(jnp.int32)
        # actual arrivals == my grants: grants <= recv_desired by
        # construction (swap and resid are both bounded by it), and each
        # sender sends exactly what I granted it
        recv_counts = grants

        send, gather_idx = _pack_rows(
            fused, order, bounds, send_counts, R, C
        )
        recv = lax.all_to_all(
            send.reshape(R, C, K), axes, split_axis=0, concat_axis=0,
            tiled=True,
        ).reshape(R * C, K)

        fused, free_stack, n_free, n_in, dropped_recv = _land_arrivals(
            fused, free_stack, n_free, recv, recv_counts, send_counts,
            gather_idx, C,
        )
        population = jnp.sum((fused[:, -1] > 0.5).astype(jnp.int32))
        stats = MigrateStats(
            sent=jnp.sum(send_counts).astype(jnp.int32)[None],
            received=n_in[None],
            population=population[None],
            backlog=backlog[None],
            dropped_recv=dropped_recv[None],
        )
        return MigrateState(fused, free_stack, n_free), stats

    return fn


def _greedy_alloc(desired: jax.Array, cap: jax.Array) -> jax.Array:
    """Allocate ``desired[s, w]`` units across sources ``s`` per column
    ``w``, greedily in source order, never exceeding ``cap[w]`` total.
    Deterministic; sources with lower index win under pressure (backlogged
    rows keep stable priority and retry next step)."""
    cum = jnp.cumsum(desired, axis=0)
    prev = cum - desired
    capb = cap[None, :]
    return jnp.clip(jnp.minimum(cum, capb) - jnp.minimum(prev, capb), 0)


def _plan_rows(seg_starts, seg_counts, order, length: int):
    """Expand per-segment (start-in-sorted-order, count) pairs into a flat
    row plan of static ``length``: entry ``j`` is the resident-slot index of
    the ``j``-th planned row (segments concatenated in segment order, the
    first ``count`` rows of each — prefix semantics). Entries ``j >= total``
    are clipped junk; callers mask by ``j < total``.

    All inputs are per-vrank 1-D: ``seg_starts``/``seg_counts`` [n_segs],
    ``order`` [n] (stable sort permutation). Pure searchsorted + gather on
    [length] vectors — cost scales with ``length``, not with n.
    """
    n = order.shape[0]
    cum = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(seg_counts).astype(jnp.int32)]
    )
    j = jnp.arange(length, dtype=jnp.int32)
    seg = jnp.clip(
        _segment_of(j, cum),
        0,
        seg_counts.shape[0] - 1,
    )
    pos = seg_starts[seg] + (j - cum[seg])
    return order[jnp.clip(pos, 0, n - 1)], cum[-1]


def shard_migrate_vranks_fn(
    domain: Domain,
    dev_grid: ProcessGrid,
    vgrid: ProcessGrid,
    capacity: int,
    ndim: int = None,
    local_budget: int = None,
):
    """Migration over a ``dev_grid * vgrid`` process grid, vranks vmapped.

    The full Cartesian grid has shape ``dev_grid.shape * vgrid.shape``
    (elementwise): device cell ``i // v`` and vrank cell ``i % v`` per axis.
    Each device owns ``V = vgrid.nranks`` subdomain slabs.

    Two-tier exchange (the TPU answer to MPI ranks on fewer nodes):

    * **On-device vrank->vrank traffic never touches a padded collective
      layout.** Migrants are routed compactly: one stable sort groups them,
      [V, V] count matrices allocate arrivals, and a single gather + single
      scatter sized to ``local_budget`` rows move exactly the migrants (the
      round-1 design paid gather+scatter over the full ``R*C`` padded
      layout — 85 ns/row over mostly-empty slots dominated the step).
      Local routing is **lossless**: senders see receiver free-slot counts
      directly (same device) and hold rows back (``backlog``) instead of
      ever dropping an arrival.
    * **Cross-device traffic** rides a ``[Dev, V, V, C, K]``
      ``lax.all_to_all`` over ICI, ``capacity`` rows per (source vrank,
      destination vrank) pair, and is **receiver-granted**: desired counts
      fly first, each destination vrank greedily grants within its free
      slots, grants fly back, and only granted rows are packed — excess
      movers backlog instead of ever hitting a full receiver (the wire
      never carries what cannot land; ``dropped_recv`` stays a safety
      counter). Mutually-full vranks on different devices trade through
      backlog (no cross-device swap financing). When ``Dev == 1`` the
      collectives and their buffers compile away entirely.

    Signature of the returned per-shard fn:
      ``MigrateState -> (MigrateState, MigrateStats)``
    with ``state.fused [V, n, K]``, ``free_stack [V, n]``, ``n_free [V]``;
    stats entries are ``[V]`` per device (global device-major order).
    ``local_budget`` bounds on-device migrants per (vrank, step) in each
    direction (default ``V * capacity``, matching the round-1 total);
    ``capacity`` bounds cross-device migrants per (source vrank,
    destination vrank) pair.
    """
    axes = dev_grid.axis_names
    V = vgrid.nranks
    Dev = dev_grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim
    M = V * C if local_budget is None else local_budget
    full_shape = tuple(
        d * v for d, v in zip(dev_grid.shape, vgrid.shape)
    )
    full_grid = ProcessGrid(full_shape, axis_names=dev_grid.axis_names)
    R_total = Dev * V
    # static plan lengths: most rows a vrank can send / receive in a step
    S_max = M + ((Dev - 1) * V * C if Dev > 1 else 0)
    P = max(M, S_max)

    def fn(state: MigrateState):
        fused, free_stack, n_free = state  # [V, n, K], [V, n], [V]
        n = fused.shape[1]
        K = fused.shape[2]
        flat = fused.reshape(V * n, K)
        me_dev = lax.axis_index(axes).astype(jnp.int32)
        my_v = jnp.arange(V, dtype=jnp.int32)  # vrank ids on this device

        def bin_one(f, v_id):
            alive = f[:, -1] > 0.5
            cell = binning.cell_of_position(
                binning.wrap_periodic(f[:, :D], domain), domain, full_grid
            )
            vshape = jnp.asarray(vgrid.shape, jnp.int32)
            dev_cell = cell // vshape
            v_cell = cell % vshape
            dest_dev = binning.rank_of_cell(dev_cell, dev_grid)
            dest_v = binning.rank_of_cell(v_cell, vgrid)
            staying = (dest_dev == me_dev) & (dest_v == v_id)
            leaving = alive & ~staying
            # device-major global destination: dev * V + vrank
            key = jnp.where(
                leaving, dest_dev * V + dest_v, R_total
            ).astype(jnp.int32)
            return key

        dest_key = jax.vmap(bin_one)(fused, my_v)  # [V, n]
        order, counts, bounds = jax.vmap(
            lambda k: binning.sorted_dest_counts(k, R_total)
        )(dest_key)  # [V, n], [V, R_total], [V, R_total + 1]
        leavers = jnp.sum(counts, axis=1).astype(jnp.int32)  # [V]

        # ---- local allocation: [V_src, V_dst] on this device ----------
        loc0 = me_dev * V
        loc_counts = lax.dynamic_slice_in_dim(counts, loc0, V, axis=1)
        loc_starts = lax.dynamic_slice_in_dim(bounds, loc0, V, axis=1)
        # per-source budget M: prefix truncation in destination order
        # (rel = each pair segment's offset within the source's local run)
        rel_start = loc_starts - loc_starts[:, :1]
        rel_end = rel_start + loc_counts
        eff = jnp.clip(
            jnp.minimum(rel_end, M) - jnp.minimum(rel_start, M),
            0,
        ).astype(jnp.int32)

        # remote sends first: they vacate slots independently of the local
        # allocation, so they seed the receiver-capacity fixpoint. With
        # Dev > 1 the sends are RECEIVER-GRANTED (lossless receive): the
        # desired per-pair counts fly first, each destination vrank
        # greedily grants within its pre-step free slots, the grants fly
        # back, and only granted rows are packed — ungranted rows stay
        # resident and retry (backlog). Remote arrivals are then
        # structurally <= n_free and the remote landing never drops.
        # (Unlike the flat path there is no cross-device swap financing —
        # the remote landing pops free slots only — so mutually-full
        # vranks on different devices trade through backlog.)
        if Dev > 1:
            desired_rem = jnp.minimum(counts, C).astype(jnp.int32)
            g_ids = jnp.arange(R_total, dtype=jnp.int32)
            is_local_g = (g_ids >= loc0) & (g_ids < loc0 + V)
            desired_rem = jnp.where(
                is_local_g[None, :], 0, desired_rem
            )  # [V_src, R_total]
            # desired -> receiver (same transpose layout as the payload)
            desired_t = desired_rem.reshape(V, Dev, V).transpose(1, 0, 2)
            recv_desired = lax.all_to_all(
                desired_t, axes, split_axis=0, concat_axis=0, tiled=True
            ).transpose(2, 0, 1).reshape(V, Dev * V)  # [V_dst, S_global]
            grants = _greedy_alloc(
                recv_desired.T, jnp.maximum(n_free, 0)
            ).T.astype(jnp.int32)  # [V_dst, S_global]
            # grants -> sender (reverse layout)
            grants_t = grants.reshape(V, Dev, V).transpose(1, 0, 2)
            grants_back = lax.all_to_all(
                grants_t, axes, split_axis=0, concat_axis=0, tiled=True
            ).transpose(2, 0, 1).reshape(V, Dev * V)  # [V_src, G_dst]
            rem_sent_full = jnp.minimum(desired_rem, grants_back)
            sent_remote = jnp.sum(rem_sent_full, axis=1).astype(jnp.int32)
            # actual arrivals == my grants (greedy allocates within each
            # source's desire, so grants <= recv_desired always)
            recv_counts_rem = grants
            n_in_rem = jnp.sum(recv_counts_rem, axis=1).astype(jnp.int32)
        else:
            sent_remote = jnp.zeros((V,), jnp.int32)
            n_in_rem = jnp.zeros((V,), jnp.int32)

        # Receiver capacity: arrivals may use current free slots PLUS slots
        # vacated by the receiver's own sends this step — otherwise
        # fully-occupied vranks that need to swap livelock. Sends depend on
        # destination capacities (circular), so solve by monotone-increasing
        # fixpoint, seeded with pairwise swaps (which are self-financing:
        # each vrank's swap arrivals exactly equal its swap departures).
        # Every truncation of the increasing orbit is safe: iteration t's
        # arrivals <= n_free + sends(t-1) + remote <= n_free + actual sends.
        # Known limit (documented): pure rotation cycles of length >= 3 at
        # exactly zero free slots everywhere stall in backlog.
        swap = jnp.minimum(eff, eff.T).astype(jnp.int32)
        # trim so swap arrivals fit the [M] arrival plan per dst, then
        # re-symmetrize (min with transpose keeps column sums <= M and
        # restores the self-financing arrivals == departures invariant)
        swap = _greedy_alloc(
            swap, jnp.full((V,), M, jnp.int32)
        ).astype(jnp.int32)
        swap = jnp.minimum(swap, swap.T)
        res_eff = eff - swap
        res = jnp.zeros_like(eff)
        # free slots already promised to granted remote arrivals are off
        # the table for local arrivals (remote lands after local and only
        # pops the stack)
        n_free_local = n_free - n_in_rem
        for _ in range(V):
            cap_res = jnp.minimum(
                M - jnp.sum(swap, axis=0),
                n_free_local + sent_remote + jnp.sum(res, axis=1),
            ).astype(jnp.int32)
            res = _greedy_alloc(res_eff, jnp.maximum(cap_res, 0)).astype(
                jnp.int32
            )
        allowed = swap + res  # [V_src, V_dst]
        sent_local = jnp.sum(allowed, axis=1).astype(jnp.int32)
        n_in_local = jnp.sum(allowed, axis=0).astype(jnp.int32)

        # ---- remote sends: padded [Dev, V_src, V_dst, C] over ICI -----
        if Dev > 1:
            # build the send buffer by index arithmetic + one flat gather;
            # global rank ids enumerate dev-major, i.e. columns 0..R_total-1
            c_i = jnp.arange(C, dtype=jnp.int32)
            cnt_sg = rem_sent_full  # [V_src, R_total]
            start_sg = bounds[:, :R_total]
            valid = c_i[None, None, :] < cnt_sg[:, :, None]
            pos = start_sg[:, :, None] + c_i[None, None, :]
            row = jnp.take_along_axis(
                order,
                jnp.clip(pos, 0, n - 1).reshape(V, -1),
                axis=1,
            ).reshape(V, Dev * V, C)
            gsrc = my_v[:, None, None] * n + row
            send = jnp.where(
                valid[..., None],
                jnp.take(flat, gsrc.reshape(-1), axis=0).reshape(
                    V, Dev * V, C, K
                ),
                0.0,
            )
            # [V_src, Dev, V_dst, C, K] -> [Dev, V_src, V_dst, C, K]
            send = send.reshape(V, Dev, V, C, K).transpose(1, 0, 2, 3, 4)
            recv = lax.all_to_all(
                send, axes, split_axis=0, concat_axis=0, tiled=True
            )
            # per-dst pools: [V_dst, Dev_src * V_src * C, K]; arrival
            # counts (recv_counts_rem) were derived locally in the grant
            # phase — no extra counts exchange needed
            recv = recv.transpose(2, 0, 1, 3, 4).reshape(V, Dev * V * C, K)

        n_sent = sent_local + sent_remote

        # ---- vacated slots: all rows leaving each vrank ---------------
        # segments: V local pairs (prefix `allowed`) then, with Dev > 1,
        # R_total global ranks (remote prefix `rem_sent_full`).
        if Dev > 1:
            seg_starts = jnp.concatenate(
                [loc_starts, bounds[:, :R_total]], axis=1
            )
            seg_counts = jnp.concatenate([allowed, rem_sent_full], axis=1)
        else:
            seg_starts = loc_starts
            seg_counts = allowed
        vacated, _tot = jax.vmap(
            lambda ss, sc, o: _plan_rows(ss, sc, o, P)
        )(seg_starts, seg_counts, order)  # [V, P]

        # ---- local arrivals: one gather sized to the budget -----------
        # dst w's arrivals: sources in order, first allowed[s, w] rows of
        # each (s -> w) segment; arrival rows are globally indexed so one
        # flat gather serves every vrank.
        cumA = jnp.concatenate(
            [jnp.zeros((1, V), jnp.int32), jnp.cumsum(allowed, axis=0)]
        )  # [V_src+1, V_dst]
        j = jnp.arange(M, dtype=jnp.int32)

        def arr_plan(w):
            cum = cumA[:, w]
            s = jnp.clip(_segment_of(j, cum), 0, V - 1)
            pos = loc_starts[s, w] + (j - cum[s])
            row = order[s, jnp.clip(pos, 0, n - 1)]
            return s * n + row  # [M] global source rows

        arr_src = jax.vmap(arr_plan)(my_v)  # [V_dst, M]
        arr_rows = jnp.take(flat, arr_src.reshape(-1), axis=0).reshape(
            V, M, K
        )

        # ---- landing plan: one flat scatter for arrivals + holes ------
        k_idx = jnp.arange(P, dtype=jnp.int32)

        def land_plan(vac, nin, nsent, nf):
            n_pop = jnp.clip(nin - nsent, 0, nf)
            pop_idx = jnp.clip(nf - 1 - (k_idx - nsent), 0, n - 1)
            target = jnp.where(
                k_idx < jnp.minimum(nin, nsent),
                vac,
                jnp.where(
                    (k_idx >= nsent) & (k_idx < nsent + n_pop),
                    jnp.zeros((), jnp.int32),  # replaced below (stack)
                    jnp.where(
                        (k_idx >= nin) & (k_idx < nsent), vac, n
                    ),
                ),
            )
            return target, n_pop, pop_idx

        targets, n_pop, pop_idx = jax.vmap(land_plan)(
            vacated, n_in_local, n_sent, n_free
        )
        pops = jnp.take_along_axis(free_stack, pop_idx, axis=1)
        use_pop = (k_idx[None, :] >= n_sent[:, None]) & (
            k_idx[None, :] < (n_sent + n_pop)[:, None]
        )
        targets = jnp.where(use_pop, pops, targets)
        # global slot ids; sentinel n -> out of range of [V*n] (dropped)
        gtargets = jnp.where(
            targets >= n, V * n, my_v[:, None] * n + targets
        )
        rows_w = jnp.zeros((V, P, K), flat.dtype).at[:, :M].set(arr_rows)
        rows_w = jnp.where(
            (k_idx[None, :] < n_in_local[:, None])[..., None], rows_w, 0.0
        )
        flat = _land_scatter(
            flat, gtargets.reshape(-1), rows_w.reshape(-1, K)
        )

        # ---- free-stack update (contiguous window blend) --------------
        n_push = jnp.maximum(n_sent - n_in_local, 0)
        free_stack, n_free = jax.vmap(_stack_push_pop)(
            free_stack, n_free, n_pop, n_push, vacated, n_in_local
        )

        # ---- remote landing: pops only, overflow counted --------------
        if Dev > 1:
            fused2 = flat.reshape(V, n, K)
            P_rem = Dev * V * C
            kr = jnp.arange(P_rem, dtype=jnp.int32)

            def land_remote(f, fs, nf, pool, rcnt):
                cum = jnp.concatenate(
                    [jnp.zeros((1,), jnp.int32), jnp.cumsum(rcnt)]
                ).astype(jnp.int32)
                nin = cum[-1]
                # cum here has Dev*V + 1 entries (scales with the whole
                # machine): comparison-count would do O(Dev*V) work per
                # query, so use the merge-sort searchsorted lowering
                s = jnp.clip(
                    jnp.searchsorted(
                        cum, kr, side="right", method="sort"
                    ).astype(jnp.int32)
                    - 1,
                    0,
                    Dev * V - 1,
                )
                src_slot = jnp.clip(
                    s * C + (kr - cum[s]), 0, P_rem - 1
                )
                arrivals = jnp.take(pool, src_slot, axis=0)
                npop = jnp.minimum(nin, nf)
                dropped = (nin - npop).astype(jnp.int32)
                pop_i = jnp.clip(nf - 1 - kr, 0, n - 1)
                tgt = jnp.where(kr < npop, fs[pop_i], n)
                f = f.at[tgt].set(
                    jnp.where((kr < nin)[:, None], arrivals, 0.0),
                    mode="drop",
                )
                return f, nf - npop, nin, dropped

            fused2, n_free, n_in_rem, dropped_recv = jax.vmap(
                land_remote
            )(fused2, free_stack, n_free, recv, recv_counts_rem)
            flat = fused2.reshape(V * n, K)
            received = n_in_local + n_in_rem
        else:
            dropped_recv = jnp.zeros((V,), jnp.int32)
            received = n_in_local

        fused = flat.reshape(V, n, K)
        backlog = (leavers - n_sent).astype(jnp.int32)
        population = jnp.sum(
            (fused[:, :, -1] > 0.5).astype(jnp.int32), axis=1
        )
        stats = MigrateStats(
            sent=n_sent,
            received=received,
            population=population,
            backlog=backlog,
            dropped_recv=dropped_recv,
        )
        return MigrateState(fused, free_stack, n_free), stats

    return fn


def shard_migrate_fn(domain: Domain, grid: ProcessGrid, capacity: int):
    """Per-field wrapper over the fused path (runs under ``shard_map``).

    Signature of the returned fn:
      ``(pos[n,D], alive[n] bool, *fields) ->
        (pos, alive, *fields, MigrateStats)``
    with identical shapes; rows where ``alive`` is False are holes. Fields
    must have 32-bit dtypes (see :func:`fuse_fields`); loops should carry
    :class:`MigrateState` across steps instead (see
    ``models.nbody.make_migrate_loop``) to skip the per-step fuse/unfuse and
    free-stack rebuild.
    """
    fused_fn = shard_migrate_fused_fn(domain, grid, capacity)

    def fn(pos, alive, *fields):
        fused, specs = fuse_fields((pos,) + tuple(fields), alive)
        state, stats = fused_fn(init_state(fused))
        out, alive_new = unfuse_fields(state.fused, specs)
        return (out[0], alive_new) + tuple(out[1:]) + (stats,)

    return fn
