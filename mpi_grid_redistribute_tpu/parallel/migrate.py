"""Resident-state migration: the fast drift-loop exchange (SURVEY.md §3.3).

The general :mod:`exchange` path re-packs every particle into canonical MPI
``Alltoallv`` receive order each step — full-array gathers plus a pool-wide
stable sort. Profiling on the real chip shows the true TPU cost model:

  * random-access scatter costs ~85 ns *per row* regardless of row width
    (a [4M,6] scatter of 256k rows is ~22 ms) — scatters must be few and
    sized to the data actually moved;
  * ``segment_sum`` histograms lower to scatter-add (~37 ms at 4M) — counts
    must come from ``searchsorted`` on already-sorted keys instead;
  * a full stable sort of 4M int32 keys is ~6 ms; elementwise binning ~3 ms.

Design (one compiled step, all static shapes):

  1. bin -> ``leaving`` mask (alive rows whose owner changed);
  2. ONE stable key sort groups leaving rows by destination; per-destination
     counts fall out of ``searchsorted`` on the sorted keys (no scatter-add);
  3. migrants beyond the per-(source,dest) ``capacity`` simply STAY resident
     and retry next step (surfaced as ``backlog`` — particles are never
     dropped on the send side);
  4. one fused ``[R, C, K]`` ``lax.all_to_all`` moves position + payload +
     alive column as a single float32 matrix (32-bit fields bitcast);
  5. arrivals land exactly in the slots vacated by departures, then in slots
     popped from a carried free-slot *stack* (contiguous dynamic-slice
     push/pop — never a scatter); one single scatter per step writes
     payload, alive flag, and vacancy markers together;
  6. arrivals beyond the shard's free slots are counted in ``dropped_recv``
     (receiver overflow is the only loss channel, and it is surfaced).

Slot order is *not* the MPI canonical order — arrivals fill arbitrary holes.
Correctness is therefore set-equality per shard against the oracle (tested),
not bit-equality; use :mod:`exchange` when canonical order matters.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning


class MigrateStats(NamedTuple):
    """Per-step migration observability (SURVEY.md §5.5). Global shapes [R]
    (one entry per shard). ``backlog`` counts migrants delayed by per-pair
    send capacity (they stay resident and retry); ``dropped_recv`` counts
    arrivals lost to receiver free-slot exhaustion — surfaced, never
    silent."""

    sent: jax.Array
    received: jax.Array
    population: jax.Array
    backlog: jax.Array
    dropped_recv: jax.Array


class MigrateState(NamedTuple):
    """Scan-carry state for the fused migration loop.

    ``fused`` is ``[n, K]`` float32: position columns, payload columns, and
    an alive column last. ``free_stack``/``n_free`` are the hole-slot stack
    (indices of dead rows; only the first ``n_free`` entries are live)."""

    fused: jax.Array
    free_stack: jax.Array
    n_free: jax.Array


def fuse_fields(arrays: Sequence[jax.Array], alive: jax.Array):
    """Pack [n, ...] arrays + alive mask into one [n, K] float32 matrix.

    32-bit dtypes are bitcast; the fused matrix only ever moves bytes
    (gather/scatter/all_to_all), so bit patterns survive exactly. The alive
    mask becomes the last column (1.0/0.0).

    Returns ``(fused, specs)``; ``specs`` drives :func:`unfuse_fields`.
    """
    n = arrays[0].shape[0]
    parts, specs = [], []
    for a in arrays:
        if a.dtype.itemsize != 4:
            raise TypeError(
                f"fused migration payload requires 32-bit dtypes, got "
                f"{a.dtype}; cast or split the field"
            )
        flat = a.reshape(n, -1)
        if flat.dtype != jnp.float32:
            flat = lax.bitcast_convert_type(flat, jnp.float32)
        parts.append(flat)
        specs.append((a.shape[1:], a.dtype))
    parts.append(alive.astype(jnp.float32)[:, None])
    return jnp.concatenate(parts, axis=1), tuple(specs)


def unfuse_fields(fused: jax.Array, specs):
    """Inverse of :func:`fuse_fields`: ``(arrays..., alive)``."""
    out = []
    col = 0
    n = fused.shape[0]
    for shape, dtype in specs:
        k = 1
        for s in shape:
            k *= s
        flat = fused[:, col : col + k]
        if dtype != jnp.float32:
            flat = lax.bitcast_convert_type(flat, dtype)
        out.append(flat.reshape((n,) + tuple(shape)))
        col += k
    alive = fused[:, -1] > 0.5
    return tuple(out), alive


def init_state(fused: jax.Array) -> MigrateState:
    """Build the free-slot stack from the fused matrix's alive column.

    One-time cost (a full argsort) at loop entry; the stack is maintained
    incrementally afterwards.
    """
    n = fused.shape[0]
    alive = fused[:, -1] > 0.5
    # dead slots first, ascending slot order
    free_stack = jnp.argsort(
        jnp.where(alive, jnp.int32(1), jnp.int32(0)), stable=True
    ).astype(jnp.int32)
    n_free = jnp.sum((~alive).astype(jnp.int32))
    return MigrateState(fused, free_stack, n_free)


def _segment_of(k: jax.Array, cum: jax.Array) -> jax.Array:
    """For flat output position(s) ``k``, the segment index under exclusive
    cumulative counts ``cum`` ([R+1], cum[0]=0): the d with
    cum[d] <= k < cum[d+1]. Pure searchsorted — no scatter."""
    return (
        jnp.searchsorted(cum, k, side="right").astype(jnp.int32) - 1
    )


def shard_migrate_fused_fn(
    domain: Domain, grid: ProcessGrid, capacity: int, ndim: int = None
):
    """Per-shard migration on fused state (runs under ``shard_map``).

    Signature of the returned fn:
      ``MigrateState -> (MigrateState, MigrateStats)``
    where ``state.fused`` is ``[n, K]`` with columns ``0:ndim`` the position
    (default ``domain.ndim``) and the last column the alive flag. Rows with
    alive 0 are holes whose contents are unspecified.
    """
    R = grid.nranks
    axes = grid.axis_names
    C = capacity
    D = domain.ndim if ndim is None else ndim

    def fn(state: MigrateState):
        fused, free_stack, n_free = state
        n, K = fused.shape
        me = lax.axis_index(axes).astype(jnp.int32)
        alive = fused[:, -1] > 0.5
        dest = binning.rank_of_position(fused[:, :D], domain, grid)
        leaving = alive & (dest != me)
        # Sentinel R: holes and staying residents sort to the tail.
        dest_key = jnp.where(leaving, dest, R).astype(jnp.int32)

        # THE sort: stable (key, slot) pairs; counts via searchsorted on the
        # sorted keys (segment_sum lowers to a ~37 ms scatter-add at 4M).
        iota = jnp.arange(n, dtype=jnp.int32)
        keys_sorted, order = lax.sort(
            (dest_key, iota), num_keys=1, is_stable=True
        )
        bounds = jnp.searchsorted(
            keys_sorted, jnp.arange(R + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32)
        full_counts = bounds[1:] - bounds[:-1]  # [R] leavers per dest
        send_counts = jnp.minimum(full_counts, C)
        backlog = jnp.sum(full_counts - send_counts).astype(jnp.int32)

        # Send slot (d, c), c < send_counts[d], takes the c-th leaver for d;
        # leavers beyond capacity keep their slots (alive stays 1 — backlog).
        c_idx = jnp.arange(C, dtype=jnp.int32)
        flat_c = jnp.tile(c_idx, R)
        flat_d = jnp.repeat(jnp.arange(R, dtype=jnp.int32), C)
        slot_valid = flat_c < send_counts[flat_d]
        src = jnp.minimum(bounds[flat_d] + flat_c, n - 1)
        gather_idx = order[src]  # [R*C] unique over valid slots
        send = jnp.where(
            slot_valid[:, None], jnp.take(fused, gather_idx, axis=0), 0.0
        ).reshape(R, C, K)

        recv_counts = lax.all_to_all(
            send_counts, axes, split_axis=0, concat_axis=0, tiled=True
        )
        recv = lax.all_to_all(
            send, axes, split_axis=0, concat_axis=0, tiled=True
        ).reshape(R * C, K)

        n_sent = jnp.sum(send_counts).astype(jnp.int32)
        n_in = jnp.sum(recv_counts).astype(jnp.int32)

        # Compact both sides by pure index arithmetic (no sort, no scatter):
        # the k-th valid send slot / arrival lives in segment d = cum^-1(k).
        cum_send = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(send_counts)]
        )
        cum_recv = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv_counts)]
        )
        k_idx = jnp.arange(R * C, dtype=jnp.int32)
        d_of_k_send = _segment_of(k_idx, cum_send)
        vacated = gather_idx[
            jnp.minimum(
                d_of_k_send * C + (k_idx - cum_send[d_of_k_send]), R * C - 1
            )
        ]  # [R*C]; first n_sent entries are the vacated slot ids
        d_of_k_recv = _segment_of(k_idx, cum_recv)
        arrivals = jnp.take(
            recv,
            jnp.minimum(
                d_of_k_recv * C + (k_idx - cum_recv[d_of_k_recv]), R * C - 1
            ),
            axis=0,
        )  # [R*C, K]; first n_in rows are real arrivals (alive column 1)

        # Landing plan for write slot j in [R*C]:
        #   j < min(n_in, n_sent): arrival j -> vacated[j]
        #   n_sent <= j < n_in:    arrival j -> popped free slot
        #   n_in <= j < n_sent:    hole marker -> vacated[j]
        # Receiver overflow: arrivals beyond n_sent + n_free drop (counted).
        n_pop = jnp.clip(n_in - n_sent, 0, n_free)
        dropped_recv = jnp.maximum(n_in - n_sent - n_free, 0).astype(
            jnp.int32
        )
        pop_idx = jnp.clip(n_free - 1 - (k_idx - n_sent), 0, n - 1)
        target = jnp.where(
            k_idx < jnp.minimum(n_in, n_sent),
            vacated,
            jnp.where(
                (k_idx >= n_sent) & (k_idx < n_sent + n_pop),
                free_stack[pop_idx],
                jnp.where(
                    (k_idx >= n_in) & (k_idx < n_sent),
                    vacated,
                    n,  # sentinel: dropped by mode="drop"
                ),
            ),
        )
        rows = jnp.where((k_idx < n_in)[:, None], arrivals, 0.0)
        # THE scatter: payload + alive flag + hole markers in one pass.
        fused = fused.at[target].set(rows, mode="drop")

        # Free-stack update (contiguous window ops only). Net excess
        # departures (n_sent - n_in when positive) were written as holes at
        # vacated[n_in : n_sent]: push them. Pops just lower n_free.
        n_push = jnp.maximum(n_sent - n_in, 0)
        new_n_free = n_free - n_pop + n_push
        # Blend the push window into the stack: read-modify-write of a
        # static [R*C] window starting at n_free (dynamic_update_slice
        # clamps the start so the window stays in bounds; compensate by
        # addressing relative to the clamped start).
        win_start = jnp.minimum(n_free, n - R * C) if n > R * C else 0
        win_start = jnp.maximum(win_start, 0).astype(jnp.int32)
        window = lax.dynamic_slice(free_stack, (win_start,), (min(R * C, n),))
        rel = n_free - win_start  # position of the stack head in the window
        w_idx = jnp.arange(min(R * C, n), dtype=jnp.int32)
        pushes = vacated[jnp.clip(n_in + (w_idx - rel), 0, R * C - 1)]
        window = jnp.where(
            (w_idx >= rel) & (w_idx < rel + n_push), pushes, window
        )
        free_stack = lax.dynamic_update_slice(free_stack, window, (win_start,))

        alive_new = fused[:, -1] > 0.5
        population = jnp.sum(alive_new.astype(jnp.int32))
        stats = MigrateStats(
            sent=n_sent[None],
            received=n_in[None],
            population=population[None],
            backlog=backlog[None],
            dropped_recv=dropped_recv[None],
        )
        return MigrateState(fused, free_stack, new_n_free), stats

    return fn


def shard_migrate_fn(domain: Domain, grid: ProcessGrid, capacity: int):
    """Per-field wrapper over the fused path (runs under ``shard_map``).

    Signature of the returned fn:
      ``(pos[n,D], alive[n] bool, *fields) ->
        (pos, alive, *fields, MigrateStats)``
    with identical shapes; rows where ``alive`` is False are holes. Fields
    must have 32-bit dtypes (see :func:`fuse_fields`); loops should carry
    :class:`MigrateState` across steps instead (see
    ``models.nbody.make_migrate_loop``) to skip the per-step fuse/unfuse and
    free-stack rebuild.
    """
    fused_fn = shard_migrate_fused_fn(domain, grid, capacity)

    def fn(pos, alive, *fields):
        fused, specs = fuse_fields((pos,) + tuple(fields), alive)
        state, stats = fused_fn(init_state(fused))
        out, alive_new = unfuse_fields(state.fused, specs)
        return (out[0], alive_new) + tuple(out[1:]) + (stats,)

    return fn
