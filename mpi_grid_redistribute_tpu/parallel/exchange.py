"""The sharded redistribute hot path (SURVEY.md §3.2, §7.3; C5, C6, C7).

Where the reference crosses the process boundary twice — ``comm.Alltoall``
for counts and ``comm.Alltoallv`` for payloads (SURVEY.md §3.2, [DRIVER]) —
this module runs the whole pipeline as one SPMD program under ``shard_map``
on a Cartesian device mesh:

    digitize -> segment_sum histogram -> stable sort-by-destination pack
    -> ``lax.all_to_all`` (counts) -> ``lax.all_to_all`` (payload pytree)
    -> stable compaction to Alltoallv receive order

Everything is static-shape (capacity-padded, SURVEY.md §7.6 "variable->fixed
size gap") so XLA compiles a single fused program per (N, capacity) bucket
and the collectives ride ICI. Overflow past capacity is counted and
returned in the stats pytree, never silent (SURVEY.md §5.3).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning, pack


class RedistributeStats(NamedTuple):
    """Per-step observability (SURVEY.md §5.5). Global (post-shard_map)
    shapes: ``send_counts`` is [R, R] indexed [source, dest];
    ``recv_counts`` is its transpose, [dest, source] (row r = what rank r
    received from each source); drop counters are [R].

    ``needed_capacity`` is the *measured* per-rank max unclipped remote
    per-destination count — the smallest per-pair ``capacity`` that would
    have sent everything (SURVEY.md §7.6 "measured capacity"); the
    adaptive-growth loop in :mod:`..api` sizes its rebuild from it."""

    send_counts: jax.Array
    recv_counts: jax.Array
    dropped_send: jax.Array
    dropped_recv: jax.Array
    needed_capacity: jax.Array


def shard_redistribute_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
):
    """Build the per-shard function (runs under ``shard_map``).

    Signature of the returned fn: ``(pos[N,D], count[1] int32, *fields)`` ->
    ``(pos_out[out_capacity,D], count_out[1], fields_out..., stats)``.
    """
    R = grid.nranks
    axes = grid.axis_names

    def fn(pos, count, *fields):
        n = pos.shape[0]
        me = lax.axis_index(axes).astype(jnp.int32)
        iota = jnp.arange(n, dtype=jnp.int32)
        valid = iota < count[0]
        dest = binning.rank_of_position(pos, domain, grid)
        dest = jnp.where(valid, dest, R).astype(jnp.int32)
        # Self-owned rows stay local (never hit the wire); the sentinel R
        # routes both invalid and self rows out of the remote pack.
        is_self = valid & (dest == me)
        dest_remote = jnp.where(is_self, R, dest)
        # One stable sort yields both the pack permutation and the
        # per-destination counts (segment_sum histograms lower to a slow
        # scatter-add on TPU — binning.sorted_dest_counts).
        order, remote_counts, _ = binning.sorted_dest_counts(dest_remote, R)
        dropped_send = jnp.sum(jnp.maximum(remote_counts - capacity, 0))
        send_counts = jnp.minimum(remote_counts, capacity)

        arrays = (pos,) + tuple(fields)
        packed = pack.pack_by_destination(
            dest_remote, remote_counts, arrays, capacity, order=order
        )
        recv_counts = lax.all_to_all(
            send_counts, axes, split_axis=0, concat_axis=0, tiled=True
        )
        recv = jax.tree.map(
            lambda a: lax.all_to_all(
                a, axes, split_axis=0, concat_axis=0, tiled=True
            ),
            packed,
        )
        out, new_count, dropped_recv = pack.compact_with_self(
            recv, recv_counts, arrays, is_self, me, out_capacity
        )
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            # remote_counts[me] is 0 (self rows carry the sentinel), so the
            # max is over genuine remote pairs.
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
        )
        return (out[0], new_count[None]) + tuple(out[1:]) + (stats,)

    return fn


def vrank_redistribute_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
):
    """R-rank canonical exchange on ONE device (virtual ranks, vmapped).

    Semantically identical to :func:`shard_redistribute_fn` over an R-way
    mesh — same binning, same stable pack, same Alltoallv receive order,
    same capacity/overflow accounting — but the ranks are vmapped slabs on
    a single device and the ``lax.all_to_all`` becomes the transpose it
    would perform on the wire ([V_src, V_dst, C, ...] ->
    [V_dst, V_src, C, ...]). Bit-compatible with the oracle (tested), so a
    single chip can run — and honestly benchmark — the full canonical
    pipeline at any R (the TPU answer to ``mpirun -n R`` on one node;
    SURVEY.md §2 process-grid topology).

    Signature: ``(pos[V, n, D], count[V], *fields[V, n, ...]) ->
    (pos_out[V, out_capacity, D], count_out[V], fields_out..., stats)``.
    """
    V = grid.nranks

    def fn(pos, count, *fields):
        n = pos.shape[1]
        me_ids = jnp.arange(V, dtype=jnp.int32)

        def pack_one(pos_v, count_v, me, *fields_v):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            dest = binning.rank_of_position(pos_v, domain, grid)
            dest = jnp.where(valid, dest, V).astype(jnp.int32)
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, V, dest)
            order, remote_counts, _ = binning.sorted_dest_counts(
                dest_remote, V
            )
            dropped_send = jnp.sum(jnp.maximum(remote_counts - capacity, 0))
            send_counts = jnp.minimum(remote_counts, capacity)
            packed = pack.pack_by_destination(
                dest_remote, remote_counts, (pos_v,) + tuple(fields_v),
                capacity, order=order,
            )
            needed = jnp.max(remote_counts).astype(jnp.int32)
            return packed, send_counts, is_self, dropped_send, needed

        packed, send_counts, is_self, dropped_send, needed = jax.vmap(
            pack_one
        )(pos, count, me_ids, *fields)
        # the wire, as a transpose: [V_src, V_dst, C, ...] -> dst-major
        recv = jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), packed)
        recv_counts = send_counts.T  # [V_dst, V_src]

        def compact_one(recv_v, recv_counts_v, me, self_mask_v, pos_v,
                        *fields_v):
            return pack.compact_with_self(
                recv_v, recv_counts_v, (pos_v,) + tuple(fields_v),
                self_mask_v, me, out_capacity,
            )

        out, new_count, dropped_recv = jax.vmap(compact_one)(
            recv, recv_counts, me_ids, is_self, pos, *fields
        )
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
        )
        return (out[0], new_count) + tuple(out[1:]) + (stats,)

    return fn


def vrank_redistribute_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
):
    """PLANAR canonical exchange: R virtual ranks on one device, ``[V, K, n]``.

    Same routing, same stable pack, same Alltoallv receive order, same
    capacity/overflow accounting as :func:`vrank_redistribute_fn` — but the
    payload is carried component-major (``K`` rows: ``D`` position
    components first, then any 32-bit fields, one row each), so no
    narrow-minor ``[n, 3]`` buffer exists anywhere. The row-major engine
    stores every such buffer in TPU's tiled T(8,128) layout (42.7x memory
    AND bandwidth for ``[n, 3]``) — measured as the canonical path's 7x
    per-row deficit vs the migrate engine (round-2 verdict item 4;
    BENCH_CONFIGS.md config 1). Routing is computed from the same wrap /
    digitize formulas (``binning.rank_of_position_planar``), so the output
    row SET and ORDER are bit-identical to the row-major engine and the
    oracle; only the storage layout differs.

    Signature: ``(fused[V, K, n], count[V]) ->
    (fused_out[V, K, out_capacity], count_out[V], stats)``; rows beyond
    ``count_out[v]`` are zero padding. Bitcast non-float32 fields on the
    way in/out (:func:`..migrate.fuse_fields` semantics, minus the alive
    row — validity here is the count prefix, as everywhere on the
    canonical path).
    """
    V = grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim

    def fn(fused, count):
        if fused.ndim != 3 or fused.shape[0] != V or fused.shape[1] < D:
            raise ValueError(
                f"fused must be [V={V}, K>={D}, n] (K rows: {D} position "
                f"components first, then 32-bit fields), got "
                f"{fused.shape}"
            )
        n = fused.shape[2]
        me_ids = jnp.arange(V, dtype=jnp.int32)

        def pack_one(f_v, count_v, me):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            dest = binning.rank_of_position_planar(f_v[:D], domain, grid)
            dest = jnp.where(valid, dest, V).astype(jnp.int32)
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, V, dest)
            order, remote_counts, bounds = binning.sorted_dest_counts(
                dest_remote, V
            )
            dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
            send_counts = jnp.minimum(remote_counts, C)
            packed, _ = pack.pack_cols(
                f_v, order, bounds[:V], send_counts, V, C
            )  # [K, V*C]
            needed = jnp.max(remote_counts).astype(jnp.int32)
            return packed, send_counts, is_self, dropped_send, needed

        packed, send_counts, is_self, dropped_send, needed = jax.vmap(
            pack_one
        )(fused, count, me_ids)
        K = fused.shape[1]
        # the wire, as a transpose: [V_src, K, V_dst, C] -> dst-major pools
        recv = (
            packed.reshape(V, K, V, C)
            .transpose(2, 1, 0, 3)
            .reshape(V, K, V * C)
        )
        recv_counts = send_counts.T  # [V_dst, V_src]

        def compact_one(pool_v, rcnt_v, me, self_mask_v, f_v):
            # Alltoallv-order compaction via a PAYLOAD-CARRYING sort: the
            # K payload rows ride the lax.sort as extra operands, so the
            # sort network itself moves the bytes. A key-sort + per-column
            # gather was measured at ~24 ns per gathered column (126 ms of
            # a 148 ms step at 4.2M rows — scripts/
            # microbench_planar_canonical.py); the payload sort does the
            # same reorder in ~43 ms: sorts are cheap on TPU, per-element
            # placement is not. Invalid columns fold into the key as
            # sentinel V (they sort last and are zero-masked, so their
            # internal order is irrelevant); iota keeps the permutation
            # unique, hence deterministic without is_stable.
            invalid, source_key = pack.pool_source_keys(
                rcnt_v, self_mask_v, me, C
            )
            source_key = jnp.where(invalid, V, source_key)
            values = jnp.concatenate([pool_v, f_v], axis=1)  # [K, V*C+n]
            m = values.shape[1]
            iota = jnp.arange(m, dtype=jnp.int32)
            operands = (source_key, iota) + tuple(
                values[k] for k in range(values.shape[0])
            )
            sorted_ops = jax.lax.sort(operands, num_keys=2, is_stable=False)
            payload = jnp.stack(sorted_ops[2:], axis=0)
            if payload.shape[1] < out_capacity:
                # pool smaller than the output: zero-pad (the tail is
                # beyond new_count <= m, so the mask below keeps it zero)
                payload = jnp.pad(
                    payload,
                    ((0, 0), (0, out_capacity - payload.shape[1])),
                )
            else:
                payload = payload[:, :out_capacity]
            new_full = jnp.sum(rcnt_v) + jnp.sum(
                self_mask_v.astype(jnp.int32)
            )
            dropped = jnp.maximum(new_full - out_capacity, 0)
            new_count = jnp.minimum(new_full, out_capacity)
            col_valid = (
                jnp.arange(out_capacity, dtype=jnp.int32) < new_count
            )
            out = jnp.where(col_valid[None, :], payload, 0)
            return out, new_count.astype(jnp.int32), dropped.astype(jnp.int32)

        out, new_count, dropped_recv = jax.vmap(compact_one)(
            recv, recv_counts, me_ids, is_self, fused
        )
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
        )
        return out, new_count, stats

    return fn


@functools.lru_cache(maxsize=64)
def build_redistribute_planar_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
):
    """jit of :func:`vrank_redistribute_planar_fn` ([V, K, n] planar)."""
    return jax.jit(
        vrank_redistribute_planar_fn(
            domain, grid, capacity, out_capacity, ndim
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
):
    """jit of :func:`vrank_redistribute_fn` (single-device, [V, n, ...])."""
    return jax.jit(vrank_redistribute_fn(domain, grid, capacity, out_capacity))


@functools.lru_cache(maxsize=64)
def build_redistribute(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    n_fields: int,
):
    """jit-compiled global redistribute over ``mesh``.

    Global layout: ``pos`` is ``[R * n_local, D]`` sharded on axis 0 over all
    mesh axes (x-major, matching rank order); ``count`` is ``[R]`` int32 with
    one entry per shard. Returns the same layout with leading dim
    ``R * out_capacity`` plus a :class:`RedistributeStats`.
    """
    axes = grid.axis_names
    spec = P(axes)
    fn = shard_redistribute_fn(domain, grid, capacity, out_capacity)
    in_specs = (spec, spec) + (spec,) * n_fields
    out_specs = (
        (spec, spec)
        + (spec,) * n_fields
        + (RedistributeStats(*([spec] * len(RedistributeStats._fields))),)
    )
    sharded = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return jax.jit(sharded)
