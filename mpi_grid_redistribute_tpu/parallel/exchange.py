"""The sharded redistribute hot path (SURVEY.md §3.2, §7.3; C5, C6, C7).

Where the reference crosses the process boundary twice — ``comm.Alltoall``
for counts and ``comm.Alltoallv`` for payloads (SURVEY.md §3.2, [DRIVER]) —
this module runs the whole pipeline as one SPMD program under ``shard_map``
on a Cartesian device mesh:

    digitize -> segment_sum histogram -> stable sort-by-destination pack
    -> ``lax.all_to_all`` (counts) -> ``lax.all_to_all`` (payload pytree)
    -> stable compaction to Alltoallv receive order

Everything is static-shape (capacity-padded, SURVEY.md §7.6 "variable->fixed
size gap") so XLA compiles a single fused program per (N, capacity) bucket
and the collectives ride ICI. Overflow past capacity is counted and
returned in the stats pytree, never silent (SURVEY.md §5.3).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from mpi_grid_redistribute_tpu.compat import shard_map

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning, pack
# rd:bin / rd:pack / rd:exchange / rd:unpack labels on the engine phases:
# a jax.named_scope lands in XLA op metadata, so Perfetto/XProf traces and
# HLO dumps group the pipeline by phase instead of op soup (telemetry
# tentpole; scan-differenced phase COSTS come from telemetry.phases.
# attribute_phases — these scopes are for trace/HLO readability).
from mpi_grid_redistribute_tpu.telemetry.phases import traced_span


ENGINES = (
    "auto", "planar", "rowmajor", "sparse", "neighbor", "hierarchical"
)


def resolve_engine(
    engine: str,
    *,
    vranks: bool = False,
    n_devices: int = 1,
    planar_ok: bool = True,
    canonical: bool = False,
    n_pods: int = 1,
    recorder=None,
) -> str:
    """Resolve a user-facing engine name to a concrete engine — the ONE
    dispatch rule shared by :class:`..api.Redistributer` (canonical
    exchange) and :func:`..models.nbody.make_migrate_loop` (resident-slot
    migrate loop), so the two surfaces cannot drift.

    Canonical exchange (``canonical=True``): ``"auto"`` picks the
    count-driven ``"sparse"`` engine on multi-device meshes (wire cost
    scales with movers — the paper's Alltoallv rationale) and
    ``"planar"`` on one device (no wire to shrink), degrading to
    ``"rowmajor"`` when the payload does not qualify for planar
    transport (``planar_ok`` — 32-bit fields that ride bitcast). The
    dense pool is reachable only via explicit ``engine="planar"`` or
    the sparse/neighbor engines' in-graph overflow fallback.
    ``"sparse"``/``"neighbor"`` are honored as asked (the neighbor
    engine is the static 3x3x3-stencil ``ppermute`` schedule).
    ``"hierarchical"`` is the two-level ICI/DCN schedule and needs a
    multi-pod mesh (``n_pods > 1``); on a flat mesh it degrades to the
    count-driven sparse engine (journaled) rather than erroring, and
    ``"auto"`` on a multi-pod multi-device mesh picks it over sparse.

    Migrate loop (``canonical=False``) returns ``"sparse"`` or
    ``"planar"``: ``"auto"``/``"sparse"`` pick the mover-sparse fast
    path exactly when the step is a single-device vrank step (``vranks``
    and ``n_devices == 1`` — see
    :func:`..parallel.migrate.shard_migrate_vranks_fn` for why
    cross-device steps stay dense); ``"rowmajor"`` and ``"neighbor"``
    have no migrate-loop meaning and raise.

    ``recorder`` (a :class:`..telemetry.StepRecorder`) journals the
    decision as an ``engine_resolved`` event — chosen engine plus the
    reason, including any degradation — so silent routing is observable.
    """
    if engine not in ENGINES:
        raise ValueError(
            f"engine must be one of {ENGINES}, got {engine!r}"
        )
    if canonical:
        if engine == "rowmajor":
            resolved, reason = "rowmajor", "explicit rowmajor"
        elif engine == "planar":
            resolved, reason = "planar", "explicit planar (dense pool)"
        elif engine == "neighbor":
            resolved, reason = "neighbor", "explicit neighbor stencil"
        elif engine == "sparse":
            resolved, reason = "sparse", "explicit count-driven sparse"
        elif engine == "hierarchical":
            if n_pods > 1:
                resolved, reason = (
                    "hierarchical", "explicit hierarchical two-level wire"
                )
            else:
                resolved, reason = (
                    "sparse",
                    "hierarchical -> sparse: flat mesh (no dcn domains)",
                )
        elif not planar_ok:
            resolved, reason = (
                "rowmajor", "auto: payload not planar-eligible"
            )
        elif n_devices > 1 and n_pods > 1:
            resolved, reason = (
                "hierarchical",
                "auto: multi-pod mesh -> hierarchical two-level wire",
            )
        elif n_devices > 1:
            resolved, reason = (
                "sparse", "auto: multi-device -> count-driven wire"
            )
        else:
            resolved, reason = (
                "planar", "auto: single device, no wire to shrink"
            )
    else:
        if engine in ("rowmajor", "neighbor", "hierarchical"):
            raise ValueError(
                f"engine={engine!r} is a canonical-exchange engine; the "
                "migrate loop accepts 'auto', 'sparse' or 'planar'"
            )
        if engine in ("auto", "sparse") and vranks and n_devices == 1:
            resolved, reason = "sparse", "migrate: single-device vranks"
        elif engine == "sparse":
            resolved, reason = (
                "planar",
                "sparse -> planar: cross-device migrate steps stay dense",
            )
        else:
            resolved, reason = "planar", "migrate: dense planar step"
    if recorder is not None:
        recorder.record(
            "engine_resolved",
            requested=engine,
            resolved=resolved,
            reason=reason,
            canonical=bool(canonical),
        )
    return resolved


class RedistributeStats(NamedTuple):
    """Per-step observability (SURVEY.md §5.5). Global (post-shard_map)
    shapes: ``send_counts`` is [R, R] indexed [source, dest];
    ``recv_counts`` is its transpose, [dest, source] (row r = what rank r
    received from each source); drop counters are [R].

    ``needed_capacity`` is the *measured* per-rank max unclipped remote
    per-destination count — the smallest per-pair ``capacity`` that would
    have sent everything (SURVEY.md §7.6 "measured capacity"); the
    adaptive-growth loop in :mod:`..api` sizes its rebuild from it; it is
    also the smallest ``mover_cap`` that would have kept the count-driven
    engines off their dense fallback.

    ``fallback`` ([R] int32, 1 where the shard's step took the in-graph
    dense fallback — mover overflow past ``mover_cap``, or out-of-stencil
    movers on the neighbor engine) is only emitted by the count-driven
    sparse/neighbor engines; it defaults to ``None`` (an EMPTY pytree
    node — zero leaves) so the dense engines' 5-leaf stats trees, their
    shard_map out_specs, and every consumer that never looks at it are
    untouched.

    ``pipeline`` ([R] int32, 1 where the step ran the software-pipelined
    steady-state branch — ISSUE 12) is only emitted by the pipelined
    resident engine and defaults to ``None`` the same way, so every
    existing 5/6-leaf stats tree is untouched.

    ``needed_cross`` ([R] int32, per-source max over destination PODS of
    the unclipped cross-pod mover total) is only emitted by the
    hierarchical two-level engine — the smallest ``cross_cap`` that
    would have carried every boundary-crossing row over the staged DCN
    hop without clipping; the adaptive-growth loop in :mod:`..api`
    ratchets its per-(pod,pod) block width from it. Defaults to ``None``
    (empty pytree node) like ``fallback``/``pipeline``."""

    send_counts: jax.Array
    recv_counts: jax.Array
    dropped_send: jax.Array
    dropped_recv: jax.Array
    needed_capacity: jax.Array
    fallback: jax.Array = None
    pipeline: jax.Array = None
    needed_cross: jax.Array = None


def shard_redistribute_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    edges=None,
):
    """Build the per-shard function (runs under ``shard_map``).

    Signature of the returned fn: ``(pos[N,D], count[1] int32, *fields)`` ->
    ``(pos_out[out_capacity,D], count_out[1], fields_out..., stats)``.
    """
    R = grid.nranks
    axes = grid.axis_names

    def fn(pos, count, *fields):
        n = pos.shape[0]
        me = lax.axis_index(axes).astype(jnp.int32)
        iota = jnp.arange(n, dtype=jnp.int32)
        valid = iota < count[0]
        with traced_span("rd:bin"):
            dest = binning.rank_of_position(pos, domain, grid, edges=edges)
            dest = jnp.where(valid, dest, R).astype(jnp.int32)
            # Self-owned rows stay local (never hit the wire); the
            # sentinel R routes both invalid and self rows out of the
            # remote pack.
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, R, dest)
            # One stable sort yields both the pack permutation and the
            # per-destination counts (segment_sum histograms lower to a
            # slow scatter-add on TPU — binning.sorted_dest_counts).
            order, remote_counts, _ = binning.sorted_dest_counts(
                dest_remote, R
            )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - capacity, 0))
        send_counts = jnp.minimum(remote_counts, capacity)

        arrays = (pos,) + tuple(fields)
        with traced_span("rd:pack"):
            packed = pack.pack_by_destination(
                dest_remote, remote_counts, arrays, capacity, order=order
            )
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
            recv = jax.tree.map(
                lambda a: lax.all_to_all(
                    a, axes, split_axis=0, concat_axis=0, tiled=True
                ),
                packed,
            )
        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = pack.compact_with_self(
                recv, recv_counts, arrays, is_self, me, out_capacity
            )
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            # remote_counts[me] is 0 (self rows carry the sentinel), so the
            # max is over genuine remote pairs.
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
        )
        return (out[0], new_count[None]) + tuple(out[1:]) + (stats,)

    return fn


def vrank_redistribute_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    edges=None,
):
    """R-rank canonical exchange on ONE device (virtual ranks, vmapped).

    Semantically identical to :func:`shard_redistribute_fn` over an R-way
    mesh — same binning, same stable pack, same Alltoallv receive order,
    same capacity/overflow accounting — but the ranks are vmapped slabs on
    a single device and the ``lax.all_to_all`` becomes the transpose it
    would perform on the wire ([V_src, V_dst, C, ...] ->
    [V_dst, V_src, C, ...]). Bit-compatible with the oracle (tested), so a
    single chip can run — and honestly benchmark — the full canonical
    pipeline at any R (the TPU answer to ``mpirun -n R`` on one node;
    SURVEY.md §2 process-grid topology).

    Signature: ``(pos[V, n, D], count[V], *fields[V, n, ...]) ->
    (pos_out[V, out_capacity, D], count_out[V], fields_out..., stats)``.
    """
    V = grid.nranks

    def fn(pos, count, *fields):
        n = pos.shape[1]
        me_ids = jnp.arange(V, dtype=jnp.int32)

        def pack_one(pos_v, count_v, me, *fields_v):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            with traced_span("rd:bin"):
                dest = binning.rank_of_position(
                    pos_v, domain, grid, edges=edges
                )
                dest = jnp.where(valid, dest, V).astype(jnp.int32)
                is_self = valid & (dest == me)
                dest_remote = jnp.where(is_self, V, dest)
                order, remote_counts, _ = binning.sorted_dest_counts(
                    dest_remote, V
                )
            dropped_send = jnp.sum(jnp.maximum(remote_counts - capacity, 0))
            send_counts = jnp.minimum(remote_counts, capacity)
            with traced_span("rd:pack"):
                packed = pack.pack_by_destination(
                    dest_remote, remote_counts, (pos_v,) + tuple(fields_v),
                    capacity, order=order,
                )
            needed = jnp.max(remote_counts).astype(jnp.int32)
            return packed, send_counts, is_self, dropped_send, needed

        packed, send_counts, is_self, dropped_send, needed = jax.vmap(
            pack_one
        )(pos, count, me_ids, *fields)
        # the wire, as a transpose: [V_src, V_dst, C, ...] -> dst-major
        with traced_span("rd:exchange"):
            recv = jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), packed)
        recv_counts = send_counts.T  # [V_dst, V_src]

        def compact_one(recv_v, recv_counts_v, me, self_mask_v, pos_v,
                        *fields_v):
            return pack.compact_with_self(
                recv_v, recv_counts_v, (pos_v,) + tuple(fields_v),
                self_mask_v, me, out_capacity,
            )

        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = jax.vmap(compact_one)(
                recv, recv_counts, me_ids, is_self, pos, *fields
            )
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
        )
        return (out[0], new_count) + tuple(out[1:]) + (stats,)

    return fn


def vrank_redistribute_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """PLANAR canonical exchange: R virtual ranks on one device, ``[V, K, n]``.

    Same routing, same stable pack, same Alltoallv receive order, same
    capacity/overflow accounting as :func:`vrank_redistribute_fn` — but the
    payload is carried component-major (``K`` rows: ``D`` position
    components first, then any 32-bit fields, one row each), so no
    narrow-minor ``[n, 3]`` buffer exists anywhere. The row-major engine
    stores every such buffer in TPU's tiled T(8,128) layout (42.7x memory
    AND bandwidth for ``[n, 3]``) — measured as the canonical path's 7x
    per-row deficit vs the migrate engine (round-2 verdict item 4;
    BENCH_CONFIGS.md config 1). Routing is computed from the same wrap /
    digitize formulas (``binning.rank_of_position_planar``), so the output
    row SET and ORDER are bit-identical to the row-major engine and the
    oracle; only the storage layout differs.

    Signature: ``(fused[V, K, n], count[V]) ->
    (fused_out[V, K, out_capacity], count_out[V], stats)``; rows beyond
    ``count_out[v]`` are zero padding. Bitcast non-float32 fields on the
    way in/out (:func:`..migrate.fuse_fields` semantics, minus the alive
    row — validity here is the count prefix, as everywhere on the
    canonical path). ``fused`` may be float32 or int32; either way the
    TRANSPORT (pack gather, wire, compaction sort) runs on an int32
    bitcast view — TPU float vector copies flush denormal f32 bit
    patterns to zero (any bitcast int < 2^23; measured through the pack
    gather at ~3k rows/shard — the hazard ops/pallas_overlay.py biases
    around), while integer lanes have no FTZ semantics, so every 32-bit
    pattern (denormals, NaN payloads, -0.0) survives bit-exactly by
    construction. Output dtype matches the input.
    """
    V = grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim

    def fn(fused, count):
        if fused.ndim != 3 or fused.shape[0] != V or fused.shape[1] < D:
            raise ValueError(
                f"fused must be [V={V}, K>={D}, n] (K rows: {D} position "
                f"components first, then 32-bit fields), got "
                f"{fused.shape}"
            )
        if fused.dtype not in (jnp.float32, jnp.int32):
            raise TypeError(
                f"fused must be float32 or int32, got {fused.dtype}"
            )
        as_f32 = fused.dtype == jnp.float32
        fi = (
            lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
        )
        pos_f = (
            fused[:, :D, :]
            if as_f32
            else lax.bitcast_convert_type(fi[:, :D, :], jnp.float32)
        )
        n = fused.shape[2]
        me_ids = jnp.arange(V, dtype=jnp.int32)

        def pack_one(fi_v, pos_v, count_v, me):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            with traced_span("rd:bin"):
                dest = binning.rank_of_position_planar(
                    pos_v, domain, grid, edges=edges
                )
                dest = jnp.where(valid, dest, V).astype(jnp.int32)
                is_self = valid & (dest == me)
                dest_remote = jnp.where(is_self, V, dest)
                order, remote_counts, bounds = binning.sorted_dest_counts(
                    dest_remote, V
                )
            dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
            send_counts = jnp.minimum(remote_counts, C)
            with traced_span("rd:pack"):
                packed, _ = pack.pack_cols(
                    fi_v, order, bounds[:V], send_counts, V, C
                )  # [K, V*C] int32
            needed = jnp.max(remote_counts).astype(jnp.int32)
            return packed, send_counts, is_self, dropped_send, needed

        packed, send_counts, is_self, dropped_send, needed = jax.vmap(
            pack_one
        )(fi, pos_f, count, me_ids)
        K = fused.shape[1]
        # the wire, as a transpose: [V_src, K, V_dst, C] -> dst-major pools
        with traced_span("rd:exchange"):
            recv = (
                packed.reshape(V, K, V, C)
                .transpose(2, 1, 0, 3)
                .reshape(V, K, V * C)
            )
        recv_counts = send_counts.T  # [V_dst, V_src]

        def compact_one(pool_v, rcnt_v, me, self_mask_v, fi_v):
            # Alltoallv-order compaction via a payload-carrying sort —
            # shared with the shard_map planar twin so the two engines
            # cannot drift (see pack.planar_compact_with_self for the
            # measured rationale). int32 operands throughout.
            return pack.planar_compact_with_self(
                pool_v, rcnt_v, me, self_mask_v, fi_v, out_capacity
            )

        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = jax.vmap(compact_one)(
                recv, recv_counts, me_ids, is_self, fi
            )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
        )
        return out, new_count, stats

    return fn


def _planar_shard_prefix(fused, count, domain, grid, D, edges, axes):
    """Shared per-shard routing prefix of the planar/sparse/neighbor
    multi-device engines: validate, bitcast to the int32 transport view,
    bin destinations, and derive the stable pack permutation + per-dest
    counts. Every multi-device planar-family engine runs EXACTLY this
    code, which is what makes the count-driven engines' routing (and the
    shared-prefix stats) bit-identical to the dense engine's by
    construction.

    Returns ``(as_f32, fi, n, me, is_self, order, remote_counts,
    bounds)``.
    """
    R = grid.nranks
    if fused.ndim != 2 or fused.shape[0] < D:
        raise ValueError(
            f"fused must be [K>={D}, n] per shard (K rows: {D} "
            f"position components first, then 32-bit fields), got "
            f"{fused.shape}"
        )
    if (
        fused.dtype not in (jnp.float32, jnp.int32)
        or np.dtype(fused.dtype).itemsize != 4
    ):
        raise TypeError(
            f"fused must be float32 or int32, got {fused.dtype}"
        )
    as_f32 = fused.dtype == jnp.float32
    fi = (
        lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
    )
    pos_f = (
        fused[:D]
        if as_f32
        else lax.bitcast_convert_type(fi[:D], jnp.float32)
    )
    n = fused.shape[1]
    me = lax.axis_index(axes).astype(jnp.int32)
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count[0]
    with traced_span("rd:bin"):
        dest = binning.rank_of_position_planar(
            pos_f, domain, grid, edges=edges
        )
        dest = jnp.where(valid, dest, R).astype(jnp.int32)
        # Self-owned columns stay local (never hit the wire); sentinel
        # R routes both invalid and self columns out of the remote
        # pack.
        is_self = valid & (dest == me)
        dest_remote = jnp.where(is_self, R, dest)
        order, remote_counts, bounds = binning.sorted_dest_counts(
            dest_remote, R
        )
    return as_f32, fi, n, me, is_self, order, remote_counts, bounds


def shard_redistribute_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """PLANAR multi-device canonical exchange (runs under ``shard_map``).

    The shard_map twin of :func:`vrank_redistribute_planar_fn`: same
    routing (``binning.rank_of_position_planar``), same ``pack_cols`` pack,
    same payload-carrying-sort compaction
    (``pack.planar_compact_with_self``), same capacity/overflow accounting
    — but the V-way transpose is a real ``lax.all_to_all`` over the mesh
    axes, riding ICI. The per-shard state is ``[K, n]`` component-major
    throughout: no narrow-minor ``[n, 3]`` buffer exists on either side of
    the wire (the row-major :func:`shard_redistribute_fn` gathers and
    exchanges ``[R, C, 3]`` buffers, every one stored in TPU's tiled
    T(8,128) layout at 42.7x the logical bytes — the measured 7x per-row
    deficit the planar engines remove, BENCH_CONFIGS.md config 1).

    Signature of the returned fn: ``(fused[K, n], count[1] int32) ->
    (fused_out[K, out_capacity], count_out[1], stats)``; columns beyond
    ``count_out`` are zero. 32-bit fields ride bitcast
    (:func:`..migrate.fuse_fields` semantics, minus the alive row).
    ``fused`` may be float32 or int32; the transport runs on an int32
    bitcast view either way (TPU denormal-flush hazard — see
    :func:`vrank_redistribute_planar_fn`); output dtype matches input.
    """
    R = grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim
    axes = grid.axis_names

    def fn(fused, count):
        as_f32, fi, n, me, is_self, order, remote_counts, bounds = (
            _planar_shard_prefix(fused, count, domain, grid, D, edges, axes)
        )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
        send_counts = jnp.minimum(remote_counts, C)
        with traced_span("rd:pack"):
            packed, _ = pack.pack_cols(
                fi, order, bounds[:R], send_counts, R, C
            )  # [K, R*C] int32, dest-major slots
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
            # The wire: tiled all_to_all splits the lane axis into R
            # chunks of C columns (chunk d -> rank d) and concatenates
            # receives source-major — exactly the [K, R*C] dst-major pool
            # the vrank twin builds with its transpose.
            pool = lax.all_to_all(
                packed, axes, split_axis=1, concat_axis=1, tiled=True
            )
        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = pack.planar_compact_with_self(
                pool, recv_counts, me, is_self, fi, out_capacity
            )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
        )
        return out, new_count[None], stats

    return fn


def shard_redistribute_planar_sharded(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """``shard_map``-wrapped (unjitted) planar exchange — composable under
    an outer jit (the public API fuses its field-bitcast boundary into the
    same program; see :mod:`..api`).

    Global layout: ``fused`` is ``[K, R * n_local]`` component-major,
    sharded on the LANE axis over all mesh axes (x-major, matching rank
    order — shard r owns columns ``[r * n_local, (r + 1) * n_local)``);
    ``count`` is ``[R]`` int32 with one entry per shard. Returns
    ``(fused_out [K, R * out_capacity], count_out [R], stats)``.
    """
    axes = grid.axis_names
    spec_f = P(None, axes)
    spec_c = P(axes)
    fn = shard_redistribute_planar_fn(
        domain, grid, capacity, out_capacity, ndim, edges=edges
    )
    # 5 explicit specs: `fallback` stays at its None default (an empty
    # pytree node) — the dense engine emits no fallback leaf.
    out_specs = (
        spec_f,
        spec_c,
        RedistributeStats(spec_c, spec_c, spec_c, spec_c, spec_c),
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec_f, spec_c), out_specs=out_specs
    )


# gridlint: fastpath-engine
def _sparse_wire(fi, order, starts, counts, R, B, axes):
    """Count-driven wire schedule: pack ``[K, R*B]`` mover blocks through
    the precomputed pack plan and ``all_to_all`` them. O(movers) work
    only — no sorts, no iota-indexed takes (G006-checked region; the
    compaction sort lives outside, in the unpack phase)."""
    with traced_span("rd:pack"):
        packed, _ = pack.pack_cols(fi, order, starts, counts, R, B)
    with traced_span("rd:exchange"):
        return lax.all_to_all(
            packed, axes, split_axis=1, concat_axis=1, tiled=True
        )


# gridlint: fastpath-engine
def _neighbor_wire(fi, plan, slot_valid, axes, perms, n_act, B):
    """Neighbor stencil wire schedule: ONE plan-indexed gather of every
    outgoing mover column, then one static-perm ``lax.ppermute`` shift
    per active stencil offset — ``n_act`` point-to-point neighbor
    exchanges of ``[K, B]`` blocks instead of a dense ``[K, R*C]``
    ``all_to_all``. O(movers) work only — no sorts, no iota-indexed
    takes (G006-checked region)."""
    K = fi.shape[0]
    with traced_span("rd:pack"):
        send = jnp.where(slot_valid[None, :], pack.gather_plan_cols(fi, plan), 0)
    send = send.reshape(K, n_act, B)
    with traced_span("rd:exchange"):
        blocks = [
            lax.ppermute(send[:, o, :], axes, perm=list(perms[o]))
            for o in range(n_act)
        ]
    return jnp.concatenate(blocks, axis=1)


def _dense_pool_wire(fi, order, starts, counts, R, C, axes):
    """Dense ``[K, R*C]`` pool wire — the count-driven engines' in-graph
    fallback, byte-identical to :func:`shard_redistribute_planar_fn`'s
    exchange. Lives at module level so the cond branch functions stay
    free of lexical collectives (the same G001 discipline as
    migrate.py's dense fallback lambda)."""
    with traced_span("rd:pack"):
        packed, _ = pack.pack_cols(fi, order, starts, counts, R, C)
    with traced_span("rd:exchange"):
        return lax.all_to_all(
            packed, axes, split_axis=1, concat_axis=1, tiled=True
        )


def _check_mover_cap(mover_cap, capacity):
    B = int(mover_cap)
    if not 1 <= B < int(capacity):
        raise ValueError(
            f"mover_cap must be in [1, capacity); got mover_cap={B}, "
            f"capacity={capacity} — at mover_cap >= capacity the "
            f"count-driven pool is no smaller than the dense one, build "
            f"the planar engine instead"
        )
    return B


def _check_cross_cap(cross_cap):
    B2 = int(cross_cap)
    if B2 < 1:
        raise ValueError(
            f"cross_cap must be >= 1, got {B2} — it is the per-(pod,pod) "
            f"condensed DCN block width of the hierarchical engine"
        )
    return B2


def _dense_intra_wire(fi, plan, slot_valid, ici_axes):
    """Dense INTRA-POD pool wire — the hierarchical engine's in-graph
    fallback for the intra stage: a ``[K, L*C]`` per-local-dest pack and
    ONE ``all_to_all`` over the ICI axes only (tiled all_to_all over a
    subset of mesh axes runs independently per value of the remaining
    — dcn — axes, so no DCN byte moves here). Lives at module level so
    the cond branch functions stay free of lexical collectives (same
    G001 discipline as :func:`_dense_pool_wire`)."""
    with traced_span("rd:pack"):
        packed = jnp.where(
            slot_valid[None, :], pack.gather_plan_cols(fi, plan), 0
        )
    with traced_span("rd:exchange"):
        return lax.all_to_all(
            packed, ici_axes, split_axis=1, concat_axis=1, tiled=True
        )


def _hier_cross_stage(fi, order, bounds_r, prefix, eff, recv_counts, pme,
                      pod_of_j, rank_table_j, dcn_axes, ici_axes, n_pods,
                      L, B2, n):
    """The staged cross-pod schedule of the hierarchical engine — runs
    OUTSIDE the intra cond (cross rows always ride it; overflow past
    ``cross_cap`` is clipped and counted, never densified, so no DCN
    collective ever widens to a dense pool).

    For each pod distance ``delta`` in ``1..n_pods-1``:

    1. condense every row bound for pod ``(pme+delta) % n_pods`` into ONE
       ``[K, B2]`` block (dest-rank-ascending segments at the statically
       prefix-summed offsets — within a pod, rank-ascending ==
       pod-local-ascending, which step 3 relies on);
    2. one ``ppermute`` over the DCN axes shifts every pod's block (and
       its per-local-dest segment lengths) ``delta`` pods forward —
       this is the ONLY payload touching DCN;
    3. the mirror rank fans the arrived block out to final destinations
       by segmenting it with an exclusive cumsum of the arrived lengths
       and one tiled ``all_to_all`` over the ICI axes.

    Returns per-delta ``(pools [K, L*B2], source-rank keys [L*B2],
    valid [L*B2])`` lists for the shared compaction."""
    j_idx = jnp.arange(B2, dtype=jnp.int32)
    m_idx = jnp.repeat(jnp.arange(L, dtype=jnp.int32), B2)
    jj = jnp.tile(j_idx, L)
    pools, keys, valids = [], [], []
    with traced_span("rd:exchange"):
        for delta in range(1, n_pods):
            q_dst = (pme + delta) % n_pods
            to_q = pod_of_j == q_dst                   # [R] bool (cross)
            hit = (
                to_q[None, :]
                & (j_idx[:, None] >= prefix[None, :])
                & (j_idx[:, None] < (prefix + eff)[None, :])
            )                                          # [B2, R]
            src_col = jnp.sum(
                jnp.where(
                    hit,
                    bounds_r[None, :] + j_idx[:, None] - prefix[None, :],
                    0,
                ),
                axis=1,
            )
            slot_valid = jnp.any(hit, axis=1)
            plan = order[jnp.minimum(src_col, n - 1)]
            blk = jnp.where(
                slot_valid[None, :], pack.gather_plan_cols(fi, plan), 0
            )                                          # [K, B2]
            # my block's per-local-dest segment lengths in the target pod
            eff_loc = eff[rank_table_j[q_dst]]         # [L]
            perm_d = [(p, (p + delta) % n_pods) for p in range(n_pods)]
            mirror = lax.ppermute(blk, dcn_axes, perm=perm_d)
            cnt_loc = lax.ppermute(eff_loc, dcn_axes, perm=perm_d)
            start_loc = jnp.concatenate(
                [jnp.zeros((1,), cnt_loc.dtype), jnp.cumsum(cnt_loc)[:-1]]
            )
            fan_valid = jj < cnt_loc[m_idx]
            fan_col = jnp.minimum(start_loc[m_idx] + jj, B2 - 1)
            fan = jnp.where(fan_valid[None, :], mirror[:, fan_col], 0)
            pool = lax.all_to_all(
                fan, ici_axes, split_axis=1, concat_axis=1, tiled=True
            )                                          # [K, L*B2]
            # chunk s slot j arrived from (pod pme-delta, local s)
            src_ranks = rank_table_j[(pme - delta) % n_pods][m_idx]
            valid_r = jj < recv_counts[src_ranks]
            pools.append(pool)
            keys.append(src_ranks.astype(jnp.int32))
            valids.append(valid_r)
    return pools, keys, valids


def shard_redistribute_sparse_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
    axes=None,
):
    """COUNT-DRIVEN multi-device canonical exchange (under ``shard_map``).

    Same routing prefix, same Alltoallv receive order, same
    capacity/overflow accounting as :func:`shard_redistribute_planar_fn`
    — but the exchanged pool is ``[K, R*mover_cap]`` instead of
    ``[K, R*capacity]``: per-step WIRE cost scales with movers, not
    residents (the paper's Alltoallv rationale, SURVEY.md §3.2). The
    counts ``all_to_all`` runs first (outside any branch); a globally
    ``pmin``-agreed guard — every per-pair mover count fits the block —
    then picks between the mover-block wire and a bit-identical dense
    fallback in ONE ``lax.cond`` (PR 4's dispatch contract: every device
    takes the same branch, so the branch-local collectives cannot
    deadlock). Both branches feed the same payload-sort compaction with
    identical valid slots in identical (source, slot) order, so the
    output is byte-identical either way; ``stats.fallback`` reports
    which branch ran, and ``stats.needed_capacity`` is exactly the
    smallest ``mover_cap`` that would have kept the fast branch.

    NOTE the compaction itself still touches every resident column (the
    canonical output contract forces a full re-pack); it is the WIRE —
    the pool riding ICI — that shrinks from residents to movers.

    ``axes`` overrides the mesh axes the collectives run over (default:
    the grid's own axis names). A :class:`..mesh.HierarchicalMesh`'s
    expanded interleaved axes keep row-major flat index == grid rank, so
    running this engine over them is bit-identical to the flat mesh —
    used by the shardcheck S004 comparison program to bill the flat
    sparse wire's cross-pod bytes to the DCN domain.
    """
    R = grid.nranks
    C = capacity
    B = _check_mover_cap(mover_cap, capacity)
    D = domain.ndim if ndim is None else ndim
    axes = grid.axis_names if axes is None else tuple(axes)

    def fn(fused, count):
        as_f32, fi, n, me, is_self, order, remote_counts, bounds = (
            _planar_shard_prefix(fused, count, domain, grid, D, edges, axes)
        )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
        send_counts = jnp.minimum(remote_counts, C)
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
        # Globally-agreed dispatch: pmin of the local fit so every device
        # takes the SAME cond branch (a disagreeing branch would strand
        # the branch-local collectives — see migrate.py's dispatch note).
        ok = (jnp.max(remote_counts) <= B).astype(jnp.int32)
        guard = lax.pmin(ok, axes)

        def _count_driven(_):
            pool = _sparse_wire(
                fi, order, bounds[:R], jnp.minimum(send_counts, B), R, B,
                axes,
            )
            with traced_span("rd:unpack"):
                return pack.planar_compact_with_self(
                    pool, recv_counts, me, is_self, fi, out_capacity
                )

        def _dense(_):
            pool = _dense_pool_wire(
                fi, order, bounds[:R], send_counts, R, C, axes
            )
            with traced_span("rd:unpack"):
                return pack.planar_compact_with_self(
                    pool, recv_counts, me, is_self, fi, out_capacity
                )

        out, new_count, dropped_recv = lax.cond(
            guard == 1, _count_driven, _dense, operand=None
        )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
            fallback=(1 - guard)[None].astype(jnp.int32),
        )
        return out, new_count[None], stats

    return fn


def shard_redistribute_neighbor_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
    axes=None,
):
    """NEIGHBOR-STENCIL multi-device canonical exchange (``shard_map``).

    Stage B of the count-driven wire: at drift-scale migration the flow
    matrix is near-neighbor-banded on a Cartesian grid, so the dense
    ``all_to_all`` is replaced by a static Moore-stencil ``ppermute``
    shift schedule (:func:`..mesh.neighbor_tables` — ≤26 neighbor
    exchanges of ``[K, mover_cap]`` blocks in 3D). The guard extends the
    sparse engine's mover-fit check with stencil membership: any mover
    bound beyond the 3x3x3 stencil flips the whole (globally
    ``pmin``-agreed) step onto the bit-identical dense fallback, journaled
    via ``stats.fallback``. Same routing prefix, same compaction ordering
    (the receive keys feed :func:`..ops.pack.planar_compact_keys` with
    the same source-major order), so output is byte-identical to
    :func:`shard_redistribute_planar_fn` on every step.
    """
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    R = grid.nranks
    C = capacity
    B = _check_mover_cap(mover_cap, capacity)
    D = domain.ndim if ndim is None else ndim
    axes = grid.axis_names if axes is None else tuple(axes)
    periodic = tuple(bool(p) for p in domain.periodic)
    _, dst_t, src_t, member = mesh_lib.neighbor_tables(grid, periodic)
    perms_all = mesh_lib.neighbor_perms(grid, periodic)
    active = tuple(o for o in range(dst_t.shape[1]) if perms_all[o])
    if not active:
        raise ValueError(
            f"neighbor engine needs a grid with at least one neighbor "
            f"link, got shape {grid.shape}"
        )
    n_act = len(active)
    perms = tuple(perms_all[o] for o in active)
    dst_j = jnp.asarray(dst_t[:, active])        # [R, n_act]
    src_j = jnp.asarray(src_t[:, active])        # [R, n_act]
    member_j = jnp.asarray(member)               # [R, R] bool

    def fn(fused, count):
        as_f32, fi, n, me, is_self, order, remote_counts, bounds = (
            _planar_shard_prefix(fused, count, domain, grid, D, edges, axes)
        )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
        send_counts = jnp.minimum(remote_counts, C)
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
        member_row = jnp.take(member_j, me, axis=0)  # [R] bool
        # in-stencil movers must fit the block; out-of-stencil pairs must
        # be EMPTY (the schedule has no route for them)
        ok = jnp.all(
            jnp.where(member_row, remote_counts <= B, remote_counts == 0)
        ).astype(jnp.int32)
        guard = lax.pmin(ok, axes)

        def _stencil(_):
            d_o = jnp.take(dst_j, me, axis=0)          # [n_act]
            d_safe = jnp.where(d_o >= 0, d_o, 0)
            sc_b = jnp.minimum(send_counts, B)
            cnt = jnp.where(d_o >= 0, sc_b[d_safe], 0)  # [n_act]
            c_idx = jnp.arange(B, dtype=jnp.int32)
            flat_c = jnp.tile(c_idx, n_act)
            off_i = jnp.repeat(jnp.arange(n_act, dtype=jnp.int32), B)
            slot_valid = flat_c < cnt[off_i]
            src_cols = jnp.minimum(bounds[d_safe][off_i] + flat_c, n - 1)
            plan = order[src_cols]
            pool = _neighbor_wire(fi, plan, slot_valid, axes, perms,
                                  n_act, B)
            # receive keys: block o arrived from src_j[me, o]; under the
            # guard every source occupies exactly ONE block (the dedup in
            # neighbor_tables), so (source, slot-iota) ordering matches
            # the dense pool's — byte-identical compaction.
            s_o = jnp.take(src_j, me, axis=0)          # [n_act]
            s_safe = jnp.where(s_o >= 0, s_o, 0)
            rc = jnp.where(s_o >= 0, recv_counts[s_safe], 0)
            valid_r = flat_c < rc[off_i]
            invalid = ~jnp.concatenate([valid_r, is_self])
            source_key = jnp.concatenate(
                [s_safe[off_i], jnp.broadcast_to(me, (n,))]
            ).astype(jnp.int32)
            values = jnp.concatenate([pool, fi], axis=1)
            new_full = (
                jnp.sum(recv_counts) + jnp.sum(is_self.astype(jnp.int32))
            )
            with traced_span("rd:unpack"):
                return pack.planar_compact_keys(
                    values, invalid, source_key, R, new_full, out_capacity
                )

        def _dense(_):
            pool = _dense_pool_wire(
                fi, order, bounds[:R], send_counts, R, C, axes
            )
            with traced_span("rd:unpack"):
                return pack.planar_compact_with_self(
                    pool, recv_counts, me, is_self, fi, out_capacity
                )

        out, new_count, dropped_recv = lax.cond(
            guard == 1, _stencil, _dense, operand=None
        )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
            fallback=(1 - guard)[None].astype(jnp.int32),
        )
        return out, new_count[None], stats

    return fn


def _validate_planar_vranks(fused, V, D):
    if fused.ndim != 3 or fused.shape[0] != V or fused.shape[1] < D:
        raise ValueError(
            f"fused must be [V={V}, K>={D}, n] (K rows: {D} position "
            f"components first, then 32-bit fields), got "
            f"{fused.shape}"
        )
    if (
        fused.dtype not in (jnp.float32, jnp.int32)
        or np.dtype(fused.dtype).itemsize != 4
    ):
        raise TypeError(
            f"fused must be float32 or int32, got {fused.dtype}"
        )
    as_f32 = fused.dtype == jnp.float32
    fi = (
        lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
    )
    pos_f = (
        fused[:, :D, :]
        if as_f32
        else lax.bitcast_convert_type(fi[:, :D, :], jnp.float32)
    )
    return as_f32, fi, pos_f


def _vrank_sparse_prefix(fi, pos_f, count, domain, grid, edges, n):
    """Vmapped routing prefix of the vrank count-driven engines — the
    same per-vrank binning/sort as :func:`vrank_redistribute_planar_fn`'s
    ``pack_one``, split from the pack so both cond branches (mover-block
    and dense widths) can share one plan."""
    V = grid.nranks
    me_ids = jnp.arange(V, dtype=jnp.int32)

    def prefix_one(fi_v, pos_v, count_v, me):
        iota = jnp.arange(n, dtype=jnp.int32)
        valid = iota < count_v
        with traced_span("rd:bin"):
            dest = binning.rank_of_position_planar(
                pos_v, domain, grid, edges=edges
            )
            dest = jnp.where(valid, dest, V).astype(jnp.int32)
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, V, dest)
            order, remote_counts, bounds = binning.sorted_dest_counts(
                dest_remote, V
            )
        return is_self, order, remote_counts, bounds

    is_self, order, remote_counts, bounds = jax.vmap(prefix_one)(
        fi, pos_f, count, me_ids
    )
    return me_ids, is_self, order, remote_counts, bounds


def vrank_redistribute_sparse_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
):
    """COUNT-DRIVEN canonical exchange, vrank twin: the HBM-side "wire"
    (the ``[V_src, K, V_dst, W]`` transpose) shrinks from ``W=capacity``
    to ``W=mover_cap`` under the same globally-agreed one-``lax.cond``
    guard as :func:`shard_redistribute_sparse_fn`; overflow falls back to
    the bit-identical dense transpose. Lets a single chip run — and
    honestly benchmark — the count-driven schedule at any R.
    """
    V = grid.nranks
    C = capacity
    B = _check_mover_cap(mover_cap, capacity)
    D = domain.ndim if ndim is None else ndim

    def fn(fused, count):
        as_f32, fi, pos_f = _validate_planar_vranks(fused, V, D)
        n = fused.shape[2]
        K = fused.shape[1]
        me_ids, is_self, order, remote_counts, bounds = (
            _vrank_sparse_prefix(fi, pos_f, count, domain, grid, edges, n)
        )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0), axis=1)
        send_counts = jnp.minimum(remote_counts, C)
        recv_counts = send_counts.T
        needed = jnp.max(remote_counts, axis=1).astype(jnp.int32)
        guard = jnp.max(remote_counts) <= B

        def _tail(W):
            def pack_one(fi_v, order_v, bounds_v, sc_v):
                with traced_span("rd:pack"):
                    packed, _ = pack.pack_cols(
                        fi_v, order_v, bounds_v[:V],
                        jnp.minimum(sc_v, W), V, W,
                    )
                return packed

            packed = jax.vmap(pack_one)(fi, order, bounds, send_counts)
            with traced_span("rd:exchange"):
                pool = (
                    packed.reshape(V, K, V, W)
                    .transpose(2, 1, 0, 3)
                    .reshape(V, K, V * W)
                )

            def compact_one(pool_v, rcnt_v, me, self_v, fi_v):
                return pack.planar_compact_with_self(
                    pool_v, rcnt_v, me, self_v, fi_v, out_capacity
                )

            with traced_span("rd:unpack"):
                return jax.vmap(compact_one)(
                    pool, recv_counts, me_ids, is_self, fi
                )

        out, new_count, dropped_recv = lax.cond(
            guard, lambda _: _tail(B), lambda _: _tail(C), operand=None
        )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
            fallback=jnp.broadcast_to(
                (~guard).astype(jnp.int32), (V,)
            ),
        )
        return out, new_count, stats

    return fn


def vrank_redistribute_neighbor_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
):
    """NEIGHBOR-STENCIL canonical exchange, vrank twin: the per-offset
    ``ppermute`` shifts become static cross-vrank block gathers through
    the same :func:`..mesh.neighbor_tables` the sharded engine ships, so
    one chip exercises the exact stencil schedule (guard, fallback, block
    order) the pod runs — bit-identical to the planar vrank engine.
    """
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
    import numpy as np

    V = grid.nranks
    C = capacity
    B = _check_mover_cap(mover_cap, capacity)
    D = domain.ndim if ndim is None else ndim
    periodic = tuple(bool(p) for p in domain.periodic)
    _, dst_t, src_t, member = mesh_lib.neighbor_tables(grid, periodic)
    perms_all = mesh_lib.neighbor_perms(grid, periodic)
    active = tuple(o for o in range(dst_t.shape[1]) if perms_all[o])
    if not active:
        raise ValueError(
            f"neighbor engine needs a grid with at least one neighbor "
            f"link, got shape {grid.shape}"
        )
    n_act = len(active)
    dst_act = dst_t[:, active]                    # np [V, n_act]
    src_act = src_t[:, active]                    # np [V, n_act]
    d_valid = jnp.asarray(dst_act >= 0)
    d_safe = jnp.asarray(np.where(dst_act >= 0, dst_act, 0))
    s_valid = jnp.asarray(src_act >= 0)
    s_safe = jnp.asarray(np.where(src_act >= 0, src_act, 0))
    member_j = jnp.asarray(member)                # [V, V] bool

    def fn(fused, count):
        as_f32, fi, pos_f = _validate_planar_vranks(fused, V, D)
        n = fused.shape[2]
        K = fused.shape[1]
        me_ids, is_self, order, remote_counts, bounds = (
            _vrank_sparse_prefix(fi, pos_f, count, domain, grid, edges, n)
        )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0), axis=1)
        send_counts = jnp.minimum(remote_counts, C)
        recv_counts = send_counts.T
        needed = jnp.max(remote_counts, axis=1).astype(jnp.int32)
        guard = jnp.all(
            jnp.where(member_j, remote_counts <= B, remote_counts == 0)
        )

        def _stencil(_):
            sc_b = jnp.minimum(send_counts, B)
            cnt = jnp.where(
                d_valid, jnp.take_along_axis(sc_b, d_safe, axis=1), 0
            )                                      # [V, n_act]
            base = jnp.take_along_axis(bounds, d_safe, axis=1)
            c_idx = jnp.arange(B, dtype=jnp.int32)
            slot_valid = (
                c_idx[None, None, :] < cnt[:, :, None]
            ).reshape(V, n_act * B)
            src_cols = jnp.minimum(
                base[:, :, None] + c_idx[None, None, :], n - 1
            ).reshape(V, n_act * B)
            plan = jnp.take_along_axis(order, src_cols, axis=1)
            with traced_span("rd:pack"):
                send = jax.vmap(pack.gather_plan_cols)(fi, plan)
                send = jnp.where(slot_valid[:, None, :], send, 0)
            blocks = send.reshape(V, K, n_act, B)
            with traced_span("rd:exchange"):
                # block o at vrank v came from src_act[v, o] — the static
                # cross-vrank gather the sharded twin does with one
                # ppermute per offset
                recv = blocks[
                    s_safe, :, jnp.arange(n_act)[None, :], :
                ]                                  # [V, n_act, K, B]
                pool = recv.transpose(0, 2, 1, 3).reshape(V, K, n_act * B)
            rc = jnp.where(
                s_valid, jnp.take_along_axis(recv_counts, s_safe, axis=1),
                0,
            )                                      # [V, n_act]
            valid_r = (
                c_idx[None, None, :] < rc[:, :, None]
            ).reshape(V, n_act * B)
            invalid = ~jnp.concatenate([valid_r, is_self], axis=1)
            source_key = jnp.concatenate(
                [
                    jnp.broadcast_to(
                        s_safe[:, :, None], (V, n_act, B)
                    ).reshape(V, n_act * B),
                    jnp.broadcast_to(me_ids[:, None], (V, n)),
                ],
                axis=1,
            ).astype(jnp.int32)
            values = jnp.concatenate([pool, fi], axis=2)
            new_full = jnp.sum(recv_counts, axis=1) + jnp.sum(
                is_self.astype(jnp.int32), axis=1
            )

            def compact_one(vals_v, inv_v, sk_v, nf_v):
                return pack.planar_compact_keys(
                    vals_v, inv_v, sk_v, V, nf_v, out_capacity
                )

            with traced_span("rd:unpack"):
                return jax.vmap(compact_one)(
                    values, invalid, source_key, new_full
                )

        def _dense(_):
            def pack_one(fi_v, order_v, bounds_v, sc_v):
                with traced_span("rd:pack"):
                    packed, _ = pack.pack_cols(
                        fi_v, order_v, bounds_v[:V], sc_v, V, C
                    )
                return packed

            packed = jax.vmap(pack_one)(fi, order, bounds, send_counts)
            with traced_span("rd:exchange"):
                pool = (
                    packed.reshape(V, K, V, C)
                    .transpose(2, 1, 0, 3)
                    .reshape(V, K, V * C)
                )

            def compact_one(pool_v, rcnt_v, me, self_v, fi_v):
                return pack.planar_compact_with_self(
                    pool_v, rcnt_v, me, self_v, fi_v, out_capacity
                )

            with traced_span("rd:unpack"):
                return jax.vmap(compact_one)(
                    pool, recv_counts, me_ids, is_self, fi
                )

        out, new_count, dropped_recv = lax.cond(
            guard, _stencil, _dense, operand=None
        )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
            fallback=jnp.broadcast_to(
                (~guard).astype(jnp.int32), (V,)
            ),
        )
        return out, new_count, stats

    return fn


def shard_redistribute_hierarchical_fn(
    domain: Domain,
    grid: ProcessGrid,
    hier,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    cross_cap: int,
    ndim: int = None,
    edges=None,
):
    """HIERARCHICAL two-level canonical exchange (``shard_map`` over the
    expanded ICI/DCN mesh of a :class:`..mesh.HierarchicalMesh`).

    Two independent wire stages replace the flat schedule (ROADMAP item
    2 — "ICI inside, DCN across"):

    * **intra-pod**: rows whose destination stays inside the sender's
      ICI domain ride the existing Moore-stencil ``ppermute`` schedule
      unchanged, over the POD-LOCAL :func:`..mesh.neighbor_tables` and
      the ICI axes only (a ``ppermute`` over a subset of mesh axes runs
      independently per pod). Out-of-stencil or over-``mover_cap``
      same-pod movers flip the (globally ``pmin``-agreed) intra stage
      onto a bit-identical dense INTRA-POD pool — still ICI-only, so
      the fallback never widens a DCN collective;
    * **cross-pod** (:func:`_hier_cross_stage`): boundary-crossing rows
      are condensed into ONE ``[K, cross_cap]`` block per destination
      pod, shifted by a single staged DCN ``ppermute`` per (pod, pod)
      distance, then fanned out to final ranks by a second intra-pod
      hop — DCN carries mover-count-driven bytes instead of dense
      fan-out. Overflow past ``cross_cap`` is clipped and counted
      (``dropped_send`` + ``stats.needed_cross``), and the adaptive
      loop in :mod:`..api` regrows ``cross_cap``, exactly like the
      ``capacity`` ratchet — there is deliberately NO dense cross-pod
      fallback in-graph.

    Both stages feed the same payload-sort compaction
    (:func:`..ops.pack.planar_compact_keys`) with per-source keys in
    within-source pack order, so the output is byte-identical to
    :func:`shard_redistribute_planar_fn` on every non-overflowing step.

    The expanded mesh interleaves ``dcn_<name>`` axes so row-major flat
    index == grid rank (see :class:`..mesh.HierarchicalMesh`); the
    counts ``all_to_all`` over ALL expanded axes is therefore
    bit-identical to the flat engines' and stats keep rank order.
    """
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    R = grid.nranks
    C = capacity
    B = _check_mover_cap(mover_cap, capacity)
    B2 = _check_cross_cap(cross_cap)
    D = domain.ndim if ndim is None else ndim
    if hier.grid != grid:
        raise ValueError(
            f"hierarchical mesh wraps grid {hier.grid.shape}, engine "
            f"built for {grid.shape}"
        )
    n_pods = hier.n_pods
    if n_pods < 2:
        raise ValueError(
            "hierarchical engine needs a multi-pod mesh (n_pods >= 2); "
            "resolve_engine degrades flat meshes to the sparse engine"
        )
    L = hier.pod_size
    axes_all = hier.axis_names
    ici_axes = hier.ici_axes
    dcn_axes = hier.dcn_axes
    periodic_local = hier.local_periodic(domain.periodic)
    _, dstL_t, srcL_t, memberL = mesh_lib.neighbor_tables(
        hier.local_grid, periodic_local
    )
    permsL_all = mesh_lib.neighbor_perms(hier.local_grid, periodic_local)
    activeL = tuple(o for o in range(dstL_t.shape[1]) if permsL_all[o])
    n_actL = len(activeL)
    permsL = tuple(permsL_all[o] for o in activeL)
    dstL_j = jnp.asarray(dstL_t[:, activeL].reshape(L, n_actL))
    srcL_j = jnp.asarray(srcL_t[:, activeL].reshape(L, n_actL))
    memberL_j = jnp.asarray(memberL)                 # [L, L] bool
    pod_of_j = jnp.asarray(hier.pod_of)              # [R]
    local_of_j = jnp.asarray(hier.local_of)          # [R]
    rank_table_j = jnp.asarray(hier.rank_table)      # [n_pods, L]
    same_np = hier.pod_of[:, None] == hier.pod_of[None, :]
    # prefix matrix: M[d', d] = 1 iff d' < d and same destination pod —
    # the condensed block's segment offsets in one matvec
    M_j = jnp.asarray(
        (
            (np.arange(R)[:, None] < np.arange(R)[None, :]) & same_np
        ).astype(np.int32)
    )
    pod_onehot_j = jnp.asarray(
        (hier.pod_of[None, :] == np.arange(n_pods)[:, None]).astype(
            np.int32
        )
    )                                                # [n_pods, R]

    def fn(fused, count):
        as_f32, fi, n, me, is_self, order, remote_counts, bounds = (
            _planar_shard_prefix(
                fused, count, domain, grid, D, edges, axes_all
            )
        )
        K = fi.shape[0]
        pme = lax.axis_index(dcn_axes).astype(jnp.int32)   # pod id
        lme = lax.axis_index(ici_axes).astype(jnp.int32)   # pod-local
        same_pod = pod_of_j == pme
        cross_mask = ~same_pod
        sc = jnp.minimum(remote_counts, C)
        sc_cross = jnp.where(cross_mask, sc, 0)
        prefix = sc_cross @ M_j                      # [R] block offsets
        eff = jnp.where(
            cross_mask, jnp.clip(B2 - prefix, 0, sc), sc
        ).astype(jnp.int32)
        dropped_send = jnp.sum(remote_counts - eff)
        send_counts = eff
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes_all, split_axis=0, concat_axis=0,
                tiled=True,
            )
        needed_cross = jnp.max(pod_onehot_j @ sc_cross).astype(jnp.int32)

        cross_pools, cross_keys, cross_valid = _hier_cross_stage(
            fi, order, bounds[:R], prefix, eff, recv_counts, pme,
            pod_of_j, rank_table_j, dcn_axes, ici_axes, n_pods, L, B2, n,
        )

        # intra guard: same-pod movers must fit the pod-local stencil
        # blocks; cross rows never enter this cond (clip-and-count).
        member_row = memberL_j[lme][local_of_j]      # [R] bool
        ok = jnp.all(
            jnp.where(
                same_pod,
                jnp.where(
                    member_row, remote_counts <= B, remote_counts == 0
                ),
                True,
            )
        ).astype(jnp.int32)
        guard = lax.pmin(ok, axes_all)

        def _finish(pool, valid_r, srckeys):
            invalid = ~jnp.concatenate([valid_r] + cross_valid + [is_self])
            source_key = jnp.concatenate(
                [srckeys] + cross_keys + [jnp.broadcast_to(me, (n,))]
            ).astype(jnp.int32)
            values = jnp.concatenate([pool] + cross_pools + [fi], axis=1)
            new_full = (
                jnp.sum(recv_counts) + jnp.sum(is_self.astype(jnp.int32))
            )
            with traced_span("rd:unpack"):
                return pack.planar_compact_keys(
                    values, invalid, source_key, R, new_full, out_capacity
                )

        def _stencil(_):
            if n_actL == 0:
                # one-rank pods: no intra links, nothing same-pod to wire
                pool = jnp.zeros((K, 0), jnp.int32)
                valid_r = jnp.zeros((0,), bool)
                srckeys = jnp.zeros((0,), jnp.int32)
                return _finish(pool, valid_r, srckeys)
            d_o = jnp.take(dstL_j, lme, axis=0)      # [n_actL] local ids
            d_safe = jnp.where(d_o >= 0, d_o, 0)
            d_glob = rank_table_j[pme, d_safe]       # [n_actL]
            sc_b = jnp.minimum(sc, B)
            cnt = jnp.where(d_o >= 0, sc_b[d_glob], 0)
            c_idx = jnp.arange(B, dtype=jnp.int32)
            flat_c = jnp.tile(c_idx, n_actL)
            off_i = jnp.repeat(jnp.arange(n_actL, dtype=jnp.int32), B)
            slot_valid = flat_c < cnt[off_i]
            src_cols = jnp.minimum(bounds[d_glob][off_i] + flat_c, n - 1)
            plan = order[src_cols]
            pool = _neighbor_wire(
                fi, plan, slot_valid, ici_axes, permsL, n_actL, B
            )
            s_o = jnp.take(srcL_j, lme, axis=0)      # [n_actL]
            s_safe = jnp.where(s_o >= 0, s_o, 0)
            s_glob = rank_table_j[pme, s_safe]
            rc = jnp.where(s_o >= 0, recv_counts[s_glob], 0)
            valid_r = flat_c < rc[off_i]
            return _finish(pool, valid_r, s_glob[off_i])

        def _dense_intra(_):
            m_all = jnp.repeat(jnp.arange(L, dtype=jnp.int32), C)
            cc = jnp.tile(jnp.arange(C, dtype=jnp.int32), L)
            d_glob_all = rank_table_j[pme, m_all]    # [L*C]
            cnt_all = jnp.where(same_pod, sc, 0)[d_glob_all]
            slot_valid = cc < cnt_all
            src_cols = jnp.minimum(bounds[d_glob_all] + cc, n - 1)
            plan = order[src_cols]
            pool = _dense_intra_wire(fi, plan, slot_valid, ici_axes)
            valid_r = cc < recv_counts[d_glob_all]
            return _finish(pool, valid_r, d_glob_all)

        out, new_count, dropped_recv = lax.cond(
            guard == 1, _stencil, _dense_intra, operand=None
        )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
            fallback=(1 - guard)[None].astype(jnp.int32),
            needed_cross=needed_cross[None],
        )
        return out, new_count[None], stats

    return fn


def vrank_redistribute_hierarchical_fn(
    domain: Domain,
    grid: ProcessGrid,
    hier,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    cross_cap: int,
    ndim: int = None,
    edges=None,
):
    """HIERARCHICAL two-level canonical exchange, vrank twin: the staged
    DCN ``ppermute`` + intra-pod fanout become static cross-vrank block
    gathers through the SAME :class:`..mesh.HierarchicalMesh` tables the
    sharded engine ships (pod ids, pod-local ranks, per-(pod,pod)
    routes), so one chip exercises the exact two-level schedule — guard,
    clip-and-count cross overflow, block order — the fleet runs.
    Bit-identical to the planar vrank engine on non-overflowing steps.
    """
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    V = grid.nranks
    C = capacity
    B = _check_mover_cap(mover_cap, capacity)
    B2 = _check_cross_cap(cross_cap)
    D = domain.ndim if ndim is None else ndim
    if hier.grid != grid:
        raise ValueError(
            f"hierarchical mesh wraps grid {hier.grid.shape}, engine "
            f"built for {grid.shape}"
        )
    n_pods = hier.n_pods
    if n_pods < 2:
        raise ValueError(
            "hierarchical engine needs a multi-pod mesh (n_pods >= 2); "
            "resolve_engine degrades flat meshes to the sparse engine"
        )
    L = hier.pod_size
    periodic_local = hier.local_periodic(domain.periodic)
    _, dstL_t, srcL_t, memberL = mesh_lib.neighbor_tables(
        hier.local_grid, periodic_local
    )
    permsL_all = mesh_lib.neighbor_perms(hier.local_grid, periodic_local)
    activeL = tuple(o for o in range(dstL_t.shape[1]) if permsL_all[o])
    n_actL = len(activeL)
    pod_of = hier.pod_of
    local_of = hier.local_of
    rank_table = hier.rank_table
    # pod-local stencil tables lifted to GLOBAL ranks per vrank
    dstL_act = dstL_t[:, activeL].reshape(L, n_actL)
    srcL_act = srcL_t[:, activeL].reshape(L, n_actL)
    dst_loc = dstL_act[local_of]                     # [V, n_actL]
    src_loc = srcL_act[local_of]
    dst_glob = np.where(
        dst_loc >= 0,
        rank_table[pod_of[:, None], np.where(dst_loc >= 0, dst_loc, 0)],
        -1,
    )
    src_glob = np.where(
        src_loc >= 0,
        rank_table[pod_of[:, None], np.where(src_loc >= 0, src_loc, 0)],
        -1,
    )
    d_valid = jnp.asarray(dst_glob >= 0)
    d_safe = jnp.asarray(np.where(dst_glob >= 0, dst_glob, 0))
    s_valid = jnp.asarray(src_glob >= 0)
    s_safe = jnp.asarray(np.where(src_glob >= 0, src_glob, 0))
    same_np = pod_of[:, None] == pod_of[None, :]
    member_j = jnp.asarray(
        same_np & memberL[local_of[:, None], local_of[None, :]]
    )
    same_j = jnp.asarray(same_np)
    cross_j = jnp.asarray(~same_np)
    M_j = jnp.asarray(
        (
            (np.arange(V)[:, None] < np.arange(V)[None, :]) & same_np
        ).astype(np.int32)
    )
    pod_onehot_t = jnp.asarray(
        (pod_of[:, None] == np.arange(n_pods)[None, :]).astype(np.int32)
    )                                                # [V, n_pods]
    # per-delta static cross tables
    to_q_np = []
    mirror_src_np = []
    dst_loc_idx_np = []
    keys_np = []
    for delta in range(n_pods):
        q_dst = (pod_of + delta) % n_pods
        to_q_np.append(pod_of[None, :] == q_dst[:, None])
        mirror_src_np.append(rank_table[(pod_of - delta) % n_pods, local_of])
        dst_loc_idx_np.append(rank_table[q_dst])     # [V, L]
        keys_np.append(
            np.repeat(rank_table[(pod_of - delta) % n_pods], B2, axis=1)
        )                                            # [V, L*B2]
    # fanout "all_to_all over ici axes" as a static within-pod gather
    row_idx_np = np.repeat(rank_table[pod_of], B2, axis=1)   # [V, L*B2]
    col_idx_np = (
        local_of[:, None] * B2 + np.tile(np.arange(B2), L)[None, :]
    )
    m_rep_np = np.repeat(np.arange(L), B2)
    # dense-intra static tables ([V, L*C])
    dloc_np = np.repeat(rank_table[pod_of], C, axis=1)
    drow_np = dloc_np
    dcol_np = local_of[:, None] * C + np.tile(np.arange(C), L)[None, :]

    def fn(fused, count):
        as_f32, fi, pos_f = _validate_planar_vranks(fused, V, D)
        n = fused.shape[2]
        K = fused.shape[1]
        me_ids, is_self, order, remote_counts, bounds = (
            _vrank_sparse_prefix(fi, pos_f, count, domain, grid, edges, n)
        )
        sc = jnp.minimum(remote_counts, C)           # [V, V]
        sc_cross = jnp.where(cross_j, sc, 0)
        prefix = sc_cross @ M_j
        eff = jnp.where(
            cross_j, jnp.clip(B2 - prefix, 0, sc), sc
        ).astype(jnp.int32)
        dropped_send = jnp.sum(remote_counts - eff, axis=1)
        send_counts = eff
        recv_counts = eff.T
        needed = jnp.max(remote_counts, axis=1).astype(jnp.int32)
        needed_cross = jnp.max(
            sc_cross @ pod_onehot_t, axis=1
        ).astype(jnp.int32)

        j_idx = jnp.arange(B2, dtype=jnp.int32)
        jj = jnp.tile(j_idx, L)
        cross_pools, cross_keys, cross_valid = [], [], []
        with traced_span("rd:exchange"):
            for delta in range(1, n_pods):
                to_q = jnp.asarray(to_q_np[delta])
                hit = (
                    to_q[:, None, :]
                    & (j_idx[None, :, None] >= prefix[:, None, :])
                    & (j_idx[None, :, None] < (prefix + eff)[:, None, :])
                )                                    # [V, B2, V]
                src_col = jnp.sum(
                    jnp.where(
                        hit,
                        bounds[:, None, :V]
                        + j_idx[None, :, None]
                        - prefix[:, None, :],
                        0,
                    ),
                    axis=2,
                )
                slot_valid = jnp.any(hit, axis=2)
                plan = jnp.take_along_axis(
                    order, jnp.minimum(src_col, n - 1), axis=1
                )
                blk = jax.vmap(pack.gather_plan_cols)(fi, plan)
                blk = jnp.where(slot_valid[:, None, :], blk, 0)
                # the DCN hop, as a static gather: vrank v's mirror
                # block came from (pod_of[v]-delta, local_of[v])
                mirror = blk[mirror_src_np[delta]]
                cnt_loc = jnp.take_along_axis(
                    eff, jnp.asarray(dst_loc_idx_np[delta]), axis=1
                )[mirror_src_np[delta]]              # [V, L] arrived lens
                start_loc = jnp.concatenate(
                    [
                        jnp.zeros((V, 1), cnt_loc.dtype),
                        jnp.cumsum(cnt_loc, axis=1)[:, :-1],
                    ],
                    axis=1,
                )
                fan_valid = jj[None, :] < cnt_loc[:, m_rep_np]
                fan_col = jnp.minimum(
                    start_loc[:, m_rep_np] + jj[None, :], B2 - 1
                )
                fan = jax.vmap(pack.gather_plan_cols)(mirror, fan_col)
                fan = jnp.where(fan_valid[:, None, :], fan, 0)
                # the intra-pod fanout hop, as a static gather
                arrived = fan[
                    row_idx_np[:, None, :],
                    jnp.arange(K)[None, :, None],
                    col_idx_np[:, None, :],
                ]                                    # [V, K, L*B2]
                keys = jnp.asarray(keys_np[delta])
                valid_r = jj[None, :] < jnp.take_along_axis(
                    recv_counts, keys, axis=1
                )
                cross_pools.append(arrived)
                cross_keys.append(keys)
                cross_valid.append(valid_r)

        guard = jnp.all(
            jnp.where(
                same_j,
                jnp.where(member_j, remote_counts <= B, remote_counts == 0),
                True,
            )
        )

        def _finish(pool, valid_r, srckeys):
            invalid = ~jnp.concatenate(
                [valid_r] + cross_valid + [is_self], axis=1
            )
            source_key = jnp.concatenate(
                [srckeys]
                + cross_keys
                + [jnp.broadcast_to(me_ids[:, None], (V, n))],
                axis=1,
            ).astype(jnp.int32)
            values = jnp.concatenate([pool] + cross_pools + [fi], axis=2)
            new_full = jnp.sum(recv_counts, axis=1) + jnp.sum(
                is_self.astype(jnp.int32), axis=1
            )

            def compact_one(vals_v, inv_v, sk_v, nf_v):
                return pack.planar_compact_keys(
                    vals_v, inv_v, sk_v, V, nf_v, out_capacity
                )

            with traced_span("rd:unpack"):
                return jax.vmap(compact_one)(
                    values, invalid, source_key, new_full
                )

        def _stencil(_):
            sc_b = jnp.minimum(sc, B)
            cnt = jnp.where(
                d_valid, jnp.take_along_axis(sc_b, d_safe, axis=1), 0
            )                                        # [V, n_actL]
            base = jnp.take_along_axis(bounds, d_safe, axis=1)
            c_idx = jnp.arange(B, dtype=jnp.int32)
            slot_valid = (
                c_idx[None, None, :] < cnt[:, :, None]
            ).reshape(V, n_actL * B)
            src_cols = jnp.minimum(
                base[:, :, None] + c_idx[None, None, :], n - 1
            ).reshape(V, n_actL * B)
            plan = jnp.take_along_axis(order, src_cols, axis=1)
            with traced_span("rd:pack"):
                send = jax.vmap(pack.gather_plan_cols)(fi, plan)
                send = jnp.where(slot_valid[:, None, :], send, 0)
            blocks = send.reshape(V, K, n_actL, B)
            with traced_span("rd:exchange"):
                recv = blocks[
                    s_safe, :, jnp.arange(n_actL)[None, :], :
                ]                                    # [V, n_actL, K, B]
                pool = recv.transpose(0, 2, 1, 3).reshape(
                    V, K, n_actL * B
                )
            rc = jnp.where(
                s_valid,
                jnp.take_along_axis(recv_counts, s_safe, axis=1),
                0,
            )
            valid_r = (
                c_idx[None, None, :] < rc[:, :, None]
            ).reshape(V, n_actL * B)
            srckeys = jnp.broadcast_to(
                s_safe[:, :, None], (V, n_actL, B)
            ).reshape(V, n_actL * B)
            return _finish(pool, valid_r, srckeys)

        def _dense_intra(_):
            cc = jnp.tile(jnp.arange(C, dtype=jnp.int32), L)
            dloc = jnp.asarray(dloc_np)
            cnt_all = jnp.take_along_axis(
                jnp.where(same_j, sc, 0), dloc, axis=1
            )                                        # [V, L*C]
            slot_valid = cc[None, :] < cnt_all
            src_cols = jnp.minimum(
                jnp.take_along_axis(bounds, dloc, axis=1) + cc[None, :],
                n - 1,
            )
            plan = jnp.take_along_axis(order, src_cols, axis=1)
            with traced_span("rd:pack"):
                packed = jax.vmap(pack.gather_plan_cols)(fi, plan)
                packed = jnp.where(slot_valid[:, None, :], packed, 0)
            with traced_span("rd:exchange"):
                pool = packed[
                    drow_np[:, None, :],
                    jnp.arange(K)[None, :, None],
                    dcol_np[:, None, :],
                ]                                    # [V, K, L*C]
            valid_r = cc[None, :] < jnp.take_along_axis(
                recv_counts, dloc, axis=1
            )
            return _finish(pool, valid_r, dloc)

        out, new_count, dropped_recv = lax.cond(
            guard, _stencil, _dense_intra, operand=None
        )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
            fallback=jnp.broadcast_to((~guard).astype(jnp.int32), (V,)),
            needed_cross=needed_cross,
        )
        return out, new_count, stats

    return fn


_COUNT_DRIVEN_SHARD_FNS = {
    "sparse": shard_redistribute_sparse_fn,
    "neighbor": shard_redistribute_neighbor_fn,
}
_COUNT_DRIVEN_VRANK_FNS = {
    "sparse": vrank_redistribute_sparse_fn,
    "neighbor": vrank_redistribute_neighbor_fn,
}

# Public roster of the count-driven engines, in roster order. progcheck's
# J000 completeness rule iterates this: adding an engine here without
# registering a traceable program in analysis/progcheck.py fails the
# registry-coverage check, so no engine ships unanalyzed.
COUNT_DRIVEN_ENGINES = tuple(_COUNT_DRIVEN_SHARD_FNS)
assert COUNT_DRIVEN_ENGINES == tuple(_COUNT_DRIVEN_VRANK_FNS)


def shard_redistribute_count_driven_sharded(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
    engine: str = "sparse",
    axes=None,
):
    """``shard_map``-wrapped count-driven exchange (``engine`` picks the
    sparse all_to_all or neighbor ppermute wire). Same global layout as
    :func:`shard_redistribute_planar_sharded`; the stats tree carries the
    extra ``fallback`` leaf ([R] int32). ``axes`` overrides the mesh
    axes (expanded hierarchical meshes — see
    :func:`shard_redistribute_sparse_fn`)."""
    axes = grid.axis_names if axes is None else tuple(axes)
    spec_f = P(None, axes)
    spec_c = P(axes)
    fn = _COUNT_DRIVEN_SHARD_FNS[engine](
        domain, grid, capacity, out_capacity, mover_cap, ndim, edges=edges,
        axes=axes,
    )
    out_specs = (
        spec_f,
        spec_c,
        RedistributeStats(
            spec_c, spec_c, spec_c, spec_c, spec_c, spec_c
        ),
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec_f, spec_c), out_specs=out_specs
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_count_driven(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
    engine: str = "sparse",
    axes=None,
):
    """jit of :func:`shard_redistribute_count_driven_sharded`."""
    return jax.jit(
        shard_redistribute_count_driven_sharded(
            mesh, domain, grid, capacity, out_capacity, mover_cap, ndim,
            edges=edges, engine=engine, axes=axes,
        )
    )


def shard_redistribute_hierarchical_sharded(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    hier,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    cross_cap: int,
    ndim: int = None,
    edges=None,
):
    """``shard_map``-wrapped hierarchical two-level exchange. ``mesh``
    must be the EXPANDED mesh (``hier.build_mesh()``); the global layout
    is identical to :func:`shard_redistribute_planar_sharded` because
    the interleaved expanded axes keep row-major flat index == grid
    rank. The stats tree carries ``fallback`` (intra stage) AND
    ``needed_cross`` ([R] int32)."""
    axes = hier.axis_names
    spec_f = P(None, axes)
    spec_c = P(axes)
    fn = shard_redistribute_hierarchical_fn(
        domain, grid, hier, capacity, out_capacity, mover_cap, cross_cap,
        ndim, edges=edges,
    )
    out_specs = (
        spec_f,
        spec_c,
        RedistributeStats(
            spec_c, spec_c, spec_c, spec_c, spec_c, spec_c, None, spec_c
        ),
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec_f, spec_c), out_specs=out_specs
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_hierarchical(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    hier,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    cross_cap: int,
    ndim: int = None,
    edges=None,
):
    """jit of :func:`shard_redistribute_hierarchical_sharded`."""
    return jax.jit(
        shard_redistribute_hierarchical_sharded(
            mesh, domain, grid, hier, capacity, out_capacity, mover_cap,
            cross_cap, ndim, edges=edges,
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_hierarchical_vranks(
    domain: Domain,
    grid: ProcessGrid,
    hier,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    cross_cap: int,
    ndim: int = None,
    edges=None,
):
    """jit of :func:`vrank_redistribute_hierarchical_fn`."""
    return jax.jit(
        vrank_redistribute_hierarchical_fn(
            domain, grid, hier, capacity, out_capacity, mover_cap,
            cross_cap, ndim, edges=edges,
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_count_driven_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    mover_cap: int,
    ndim: int = None,
    edges=None,
    engine: str = "sparse",
):
    """jit of the count-driven vrank twins ([V, K, n] planar)."""
    return jax.jit(
        _COUNT_DRIVEN_VRANK_FNS[engine](
            domain, grid, capacity, out_capacity, mover_cap, ndim,
            edges=edges,
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_planar(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """jit of :func:`shard_redistribute_planar_sharded` (global planar)."""
    return jax.jit(
        shard_redistribute_planar_sharded(
            mesh, domain, grid, capacity, out_capacity, ndim, edges=edges
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_planar_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """jit of :func:`vrank_redistribute_planar_fn` ([V, K, n] planar)."""
    return jax.jit(
        vrank_redistribute_planar_fn(
            domain, grid, capacity, out_capacity, ndim, edges=edges
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    edges=None,
):
    """jit of :func:`vrank_redistribute_fn` (single-device, [V, n, ...])."""
    return jax.jit(
        vrank_redistribute_fn(domain, grid, capacity, out_capacity, edges)
    )


@functools.lru_cache(maxsize=64)
def build_redistribute(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    n_fields: int,
    edges=None,
):
    """jit-compiled global redistribute over ``mesh``.

    Global layout: ``pos`` is ``[R * n_local, D]`` sharded on axis 0 over all
    mesh axes (x-major, matching rank order); ``count`` is ``[R]`` int32 with
    one entry per shard. Returns the same layout with leading dim
    ``R * out_capacity`` plus a :class:`RedistributeStats`.
    """
    axes = grid.axis_names
    spec = P(axes)
    fn = shard_redistribute_fn(domain, grid, capacity, out_capacity, edges)
    in_specs = (spec, spec) + (spec,) * n_fields
    out_specs = (
        (spec, spec)
        + (spec,) * n_fields
        # 5 explicit specs: no fallback leaf on the row-major engine
        + (RedistributeStats(spec, spec, spec, spec, spec),)
    )
    sharded = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# Two-phase (start/finish) exchange surface — the software-pipelined
# resident engine's dispatch point (ISSUE 12).
# ---------------------------------------------------------------------------


class TwoPhaseExchange(NamedTuple):
    """Resolution record for the two-phase exchange surface (ISSUE 12).

    ``armed`` is the STATIC (build-time) verdict: True means the
    pipelined schedule is feasible and ``bundle`` carries the engine
    implementation (a :class:`..migrate.VrankTwoPhase` for the
    single-device vranks mesh, or any object with ``issue``/``complete``
    attributes such as the split :func:`..migrate.shard_migrate_fused_fn`);
    False means the caller must build the sequential body instead
    (``bundle`` is None) and ``reason`` says why. The decision is
    journaled as an ``engine_resolved`` event, same shape as
    :func:`resolve_engine`'s, so silent degradation is observable."""

    engine: str
    armed: bool
    reason: str
    bundle: object = None


def resolve_two_phase(
    engine: str,
    *,
    chunk: int,
    planar_ok: bool = True,
    ragged: bool = False,
    vranks: bool = False,
    n_devices: int = 1,
    n_pods: int = 1,
    build=None,
    recorder=None,
) -> TwoPhaseExchange:
    """Resolve whether the software-pipelined two-phase schedule may arm
    (ISSUE 12) — the ONE dispatch rule shared by
    :func:`..service.pipeline.make_pipelined_chunk_fn` and any future
    pipelined caller, mirroring :func:`resolve_engine`'s role for the
    one-shot engines.

    The pipelined steady state needs (a) at least two scan iterations so
    an exchange can sit in flight across an iteration boundary
    (``chunk >= 2``), (b) a planar-eligible payload (32-bit fields that
    ride bitcast, ``planar_ok``), (c) a rectangular receive side
    (``not ragged`` — out_capacity == n_local, so landed rows never
    re-compact mid-chunk), and (d) a topology whose exchange completes
    on one device (single-device vranks — cross-device two-phase needs
    an async collective surface this engine does not have yet). Any
    miss degrades to the sequential body at BUILD time; the runtime
    ``lax.cond`` inside the pipelined scan handles only the dynamic
    (backlog) case.

    ``build`` is a zero-arg callable constructing the engine bundle
    (deferred so degraded resolutions never trace it); ``recorder``
    journals the decision as ``engine_resolved`` with
    ``requested=engine``, ``resolved`` in {"pipeline", "sequential"}
    and one of the six "pipeline: ..." reason strings
    (telemetry/SCHEMA.md) — a multi-pod hierarchical topology
    (``n_pods > 1``) degrades like the multi-device case: the two-level
    wire has no two-phase surface yet.
    """
    if engine not in ENGINES:
        raise ValueError(
            f"engine must be one of {ENGINES}, got {engine!r}"
        )
    if chunk < 2:
        armed, reason = False, "pipeline: chunk < 2 — sequential body"
    elif not planar_ok:
        armed, reason = (
            False, "pipeline: payload not planar-eligible — sequential body"
        )
    elif ragged:
        armed, reason = (
            False, "pipeline: ragged receive capacity — sequential body"
        )
    elif not (vranks or n_devices == 1):
        armed, reason = (
            False, "pipeline: multi-device topology — sequential body"
        )
    elif n_pods > 1:
        armed, reason = (
            False,
            "pipeline: hierarchical multi-pod topology — sequential body",
        )
    else:
        armed, reason = True, "pipeline: armed (vranks planar two-phase)"
    if recorder is not None:
        recorder.record(
            "engine_resolved",
            requested=engine,
            resolved="pipeline" if armed else "sequential",
            reason=reason,
            canonical=False,
        )
    bundle = build() if (armed and build is not None) else None
    return TwoPhaseExchange(engine, armed, reason, bundle)


def _two_phase_impl(handle):
    impl = handle.bundle if isinstance(handle, TwoPhaseExchange) else handle
    if impl is None:
        raise TypeError(
            "two-phase exchange is not armed (degraded resolution: "
            f"{getattr(handle, 'reason', 'no bundle')!r}) — build the "
            "sequential body instead"
        )
    return impl


def start_exchange(handle, *args):
    """Phase 1 of the two-phase exchange: issue the routing plan (and,
    for engines with a real wire, put the payload in flight). Dispatches
    through a :class:`TwoPhaseExchange` handle — or directly through any
    engine exposing ``issue`` (the split
    :func:`..migrate.shard_migrate_fused_fn` and
    :class:`..migrate.VrankTwoPhase` both do). Reads nothing the
    landing mutates, so a pipelined caller may issue step k+1 while
    step k is still unconsumed."""
    impl = _two_phase_impl(handle)
    return impl.issue(*args)


def finish_exchange(handle, *args):
    """Phase 2 of the two-phase exchange: consume an in-flight plan and
    land the exchanged rows (free-stack update fused into the landing
    kernel). Dispatches to the engine's ``complete`` (flat migrate
    engine) or ``land`` (vranks planar two-phase) half."""
    impl = _two_phase_impl(handle)
    finish = getattr(impl, "complete", None)
    if finish is None:
        finish = impl.land
    return finish(*args)
