"""The sharded redistribute hot path (SURVEY.md §3.2, §7.3; C5, C6, C7).

Where the reference crosses the process boundary twice — ``comm.Alltoall``
for counts and ``comm.Alltoallv`` for payloads (SURVEY.md §3.2, [DRIVER]) —
this module runs the whole pipeline as one SPMD program under ``shard_map``
on a Cartesian device mesh:

    digitize -> segment_sum histogram -> stable sort-by-destination pack
    -> ``lax.all_to_all`` (counts) -> ``lax.all_to_all`` (payload pytree)
    -> stable compaction to Alltoallv receive order

Everything is static-shape (capacity-padded, SURVEY.md §7.6 "variable->fixed
size gap") so XLA compiles a single fused program per (N, capacity) bucket
and the collectives ride ICI. Overflow past capacity is counted and
returned in the stats pytree, never silent (SURVEY.md §5.3).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from mpi_grid_redistribute_tpu.compat import shard_map

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning, pack
# rd:bin / rd:pack / rd:exchange / rd:unpack labels on the engine phases:
# a jax.named_scope lands in XLA op metadata, so Perfetto/XProf traces and
# HLO dumps group the pipeline by phase instead of op soup (telemetry
# tentpole; scan-differenced phase COSTS come from telemetry.phases.
# attribute_phases — these scopes are for trace/HLO readability).
from mpi_grid_redistribute_tpu.telemetry.phases import traced_span


ENGINES = ("auto", "planar", "rowmajor", "sparse")


def resolve_engine(
    engine: str,
    *,
    vranks: bool = False,
    n_devices: int = 1,
    planar_ok: bool = True,
    canonical: bool = False,
) -> str:
    """Resolve a user-facing engine name to a concrete engine — the ONE
    dispatch rule shared by :class:`..api.Redistributer` (canonical
    exchange) and :func:`..models.nbody.make_migrate_loop` (resident-slot
    migrate loop), so the two surfaces cannot drift.

    Canonical exchange (``canonical=True``) returns ``"planar"`` or
    ``"rowmajor"``: ``"auto"`` picks planar when the payload qualifies
    (``planar_ok`` — 32-bit fields that ride bitcast); ``"sparse"``
    resolves to planar because the canonical output contract (MPI
    Alltoallv receive order) forces a full re-pack of every resident row
    each call — an O(movers) step cannot exist there.

    Migrate loop (``canonical=False``) returns ``"sparse"`` or
    ``"planar"``: ``"auto"``/``"sparse"`` pick the mover-sparse fast
    path exactly when the step is a single-device vrank step (``vranks``
    and ``n_devices == 1`` — see
    :func:`..parallel.migrate.shard_migrate_vranks_fn` for why
    cross-device steps stay dense); ``"rowmajor"`` has no migrate-loop
    meaning and raises.
    """
    if engine not in ENGINES:
        raise ValueError(
            f"engine must be one of {ENGINES}, got {engine!r}"
        )
    if canonical:
        if engine == "rowmajor":
            return "rowmajor"
        # "auto"/"planar"/"sparse" -> planar when the payload qualifies;
        # "auto" falls back to rowmajor otherwise ("planar" is an
        # explicit ask — the caller surfaces the typed payload error)
        if engine == "auto" and not planar_ok:
            return "rowmajor"
        return "planar"
    if engine == "rowmajor":
        raise ValueError(
            "engine='rowmajor' is a canonical-exchange engine; the "
            "migrate loop accepts 'auto', 'sparse' or 'planar'"
        )
    if engine in ("auto", "sparse") and vranks and n_devices == 1:
        return "sparse"
    return "planar"


class RedistributeStats(NamedTuple):
    """Per-step observability (SURVEY.md §5.5). Global (post-shard_map)
    shapes: ``send_counts`` is [R, R] indexed [source, dest];
    ``recv_counts`` is its transpose, [dest, source] (row r = what rank r
    received from each source); drop counters are [R].

    ``needed_capacity`` is the *measured* per-rank max unclipped remote
    per-destination count — the smallest per-pair ``capacity`` that would
    have sent everything (SURVEY.md §7.6 "measured capacity"); the
    adaptive-growth loop in :mod:`..api` sizes its rebuild from it."""

    send_counts: jax.Array
    recv_counts: jax.Array
    dropped_send: jax.Array
    dropped_recv: jax.Array
    needed_capacity: jax.Array


def shard_redistribute_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    edges=None,
):
    """Build the per-shard function (runs under ``shard_map``).

    Signature of the returned fn: ``(pos[N,D], count[1] int32, *fields)`` ->
    ``(pos_out[out_capacity,D], count_out[1], fields_out..., stats)``.
    """
    R = grid.nranks
    axes = grid.axis_names

    def fn(pos, count, *fields):
        n = pos.shape[0]
        me = lax.axis_index(axes).astype(jnp.int32)
        iota = jnp.arange(n, dtype=jnp.int32)
        valid = iota < count[0]
        with traced_span("rd:bin"):
            dest = binning.rank_of_position(pos, domain, grid, edges=edges)
            dest = jnp.where(valid, dest, R).astype(jnp.int32)
            # Self-owned rows stay local (never hit the wire); the
            # sentinel R routes both invalid and self rows out of the
            # remote pack.
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, R, dest)
            # One stable sort yields both the pack permutation and the
            # per-destination counts (segment_sum histograms lower to a
            # slow scatter-add on TPU — binning.sorted_dest_counts).
            order, remote_counts, _ = binning.sorted_dest_counts(
                dest_remote, R
            )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - capacity, 0))
        send_counts = jnp.minimum(remote_counts, capacity)

        arrays = (pos,) + tuple(fields)
        with traced_span("rd:pack"):
            packed = pack.pack_by_destination(
                dest_remote, remote_counts, arrays, capacity, order=order
            )
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
            recv = jax.tree.map(
                lambda a: lax.all_to_all(
                    a, axes, split_axis=0, concat_axis=0, tiled=True
                ),
                packed,
            )
        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = pack.compact_with_self(
                recv, recv_counts, arrays, is_self, me, out_capacity
            )
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            # remote_counts[me] is 0 (self rows carry the sentinel), so the
            # max is over genuine remote pairs.
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
        )
        return (out[0], new_count[None]) + tuple(out[1:]) + (stats,)

    return fn


def vrank_redistribute_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    edges=None,
):
    """R-rank canonical exchange on ONE device (virtual ranks, vmapped).

    Semantically identical to :func:`shard_redistribute_fn` over an R-way
    mesh — same binning, same stable pack, same Alltoallv receive order,
    same capacity/overflow accounting — but the ranks are vmapped slabs on
    a single device and the ``lax.all_to_all`` becomes the transpose it
    would perform on the wire ([V_src, V_dst, C, ...] ->
    [V_dst, V_src, C, ...]). Bit-compatible with the oracle (tested), so a
    single chip can run — and honestly benchmark — the full canonical
    pipeline at any R (the TPU answer to ``mpirun -n R`` on one node;
    SURVEY.md §2 process-grid topology).

    Signature: ``(pos[V, n, D], count[V], *fields[V, n, ...]) ->
    (pos_out[V, out_capacity, D], count_out[V], fields_out..., stats)``.
    """
    V = grid.nranks

    def fn(pos, count, *fields):
        n = pos.shape[1]
        me_ids = jnp.arange(V, dtype=jnp.int32)

        def pack_one(pos_v, count_v, me, *fields_v):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            with traced_span("rd:bin"):
                dest = binning.rank_of_position(
                    pos_v, domain, grid, edges=edges
                )
                dest = jnp.where(valid, dest, V).astype(jnp.int32)
                is_self = valid & (dest == me)
                dest_remote = jnp.where(is_self, V, dest)
                order, remote_counts, _ = binning.sorted_dest_counts(
                    dest_remote, V
                )
            dropped_send = jnp.sum(jnp.maximum(remote_counts - capacity, 0))
            send_counts = jnp.minimum(remote_counts, capacity)
            with traced_span("rd:pack"):
                packed = pack.pack_by_destination(
                    dest_remote, remote_counts, (pos_v,) + tuple(fields_v),
                    capacity, order=order,
                )
            needed = jnp.max(remote_counts).astype(jnp.int32)
            return packed, send_counts, is_self, dropped_send, needed

        packed, send_counts, is_self, dropped_send, needed = jax.vmap(
            pack_one
        )(pos, count, me_ids, *fields)
        # the wire, as a transpose: [V_src, V_dst, C, ...] -> dst-major
        with traced_span("rd:exchange"):
            recv = jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), packed)
        recv_counts = send_counts.T  # [V_dst, V_src]

        def compact_one(recv_v, recv_counts_v, me, self_mask_v, pos_v,
                        *fields_v):
            return pack.compact_with_self(
                recv_v, recv_counts_v, (pos_v,) + tuple(fields_v),
                self_mask_v, me, out_capacity,
            )

        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = jax.vmap(compact_one)(
                recv, recv_counts, me_ids, is_self, pos, *fields
            )
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
        )
        return (out[0], new_count) + tuple(out[1:]) + (stats,)

    return fn


def vrank_redistribute_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """PLANAR canonical exchange: R virtual ranks on one device, ``[V, K, n]``.

    Same routing, same stable pack, same Alltoallv receive order, same
    capacity/overflow accounting as :func:`vrank_redistribute_fn` — but the
    payload is carried component-major (``K`` rows: ``D`` position
    components first, then any 32-bit fields, one row each), so no
    narrow-minor ``[n, 3]`` buffer exists anywhere. The row-major engine
    stores every such buffer in TPU's tiled T(8,128) layout (42.7x memory
    AND bandwidth for ``[n, 3]``) — measured as the canonical path's 7x
    per-row deficit vs the migrate engine (round-2 verdict item 4;
    BENCH_CONFIGS.md config 1). Routing is computed from the same wrap /
    digitize formulas (``binning.rank_of_position_planar``), so the output
    row SET and ORDER are bit-identical to the row-major engine and the
    oracle; only the storage layout differs.

    Signature: ``(fused[V, K, n], count[V]) ->
    (fused_out[V, K, out_capacity], count_out[V], stats)``; rows beyond
    ``count_out[v]`` are zero padding. Bitcast non-float32 fields on the
    way in/out (:func:`..migrate.fuse_fields` semantics, minus the alive
    row — validity here is the count prefix, as everywhere on the
    canonical path). ``fused`` may be float32 or int32; either way the
    TRANSPORT (pack gather, wire, compaction sort) runs on an int32
    bitcast view — TPU float vector copies flush denormal f32 bit
    patterns to zero (any bitcast int < 2^23; measured through the pack
    gather at ~3k rows/shard — the hazard ops/pallas_overlay.py biases
    around), while integer lanes have no FTZ semantics, so every 32-bit
    pattern (denormals, NaN payloads, -0.0) survives bit-exactly by
    construction. Output dtype matches the input.
    """
    V = grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim

    def fn(fused, count):
        if fused.ndim != 3 or fused.shape[0] != V or fused.shape[1] < D:
            raise ValueError(
                f"fused must be [V={V}, K>={D}, n] (K rows: {D} position "
                f"components first, then 32-bit fields), got "
                f"{fused.shape}"
            )
        if fused.dtype not in (jnp.float32, jnp.int32):
            raise TypeError(
                f"fused must be float32 or int32, got {fused.dtype}"
            )
        as_f32 = fused.dtype == jnp.float32
        fi = (
            lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
        )
        pos_f = (
            fused[:, :D, :]
            if as_f32
            else lax.bitcast_convert_type(fi[:, :D, :], jnp.float32)
        )
        n = fused.shape[2]
        me_ids = jnp.arange(V, dtype=jnp.int32)

        def pack_one(fi_v, pos_v, count_v, me):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            with traced_span("rd:bin"):
                dest = binning.rank_of_position_planar(
                    pos_v, domain, grid, edges=edges
                )
                dest = jnp.where(valid, dest, V).astype(jnp.int32)
                is_self = valid & (dest == me)
                dest_remote = jnp.where(is_self, V, dest)
                order, remote_counts, bounds = binning.sorted_dest_counts(
                    dest_remote, V
                )
            dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
            send_counts = jnp.minimum(remote_counts, C)
            with traced_span("rd:pack"):
                packed, _ = pack.pack_cols(
                    fi_v, order, bounds[:V], send_counts, V, C
                )  # [K, V*C] int32
            needed = jnp.max(remote_counts).astype(jnp.int32)
            return packed, send_counts, is_self, dropped_send, needed

        packed, send_counts, is_self, dropped_send, needed = jax.vmap(
            pack_one
        )(fi, pos_f, count, me_ids)
        K = fused.shape[1]
        # the wire, as a transpose: [V_src, K, V_dst, C] -> dst-major pools
        with traced_span("rd:exchange"):
            recv = (
                packed.reshape(V, K, V, C)
                .transpose(2, 1, 0, 3)
                .reshape(V, K, V * C)
            )
        recv_counts = send_counts.T  # [V_dst, V_src]

        def compact_one(pool_v, rcnt_v, me, self_mask_v, fi_v):
            # Alltoallv-order compaction via a payload-carrying sort —
            # shared with the shard_map planar twin so the two engines
            # cannot drift (see pack.planar_compact_with_self for the
            # measured rationale). int32 operands throughout.
            return pack.planar_compact_with_self(
                pool_v, rcnt_v, me, self_mask_v, fi_v, out_capacity
            )

        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = jax.vmap(compact_one)(
                recv, recv_counts, me_ids, is_self, fi
            )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32), axis=1)
        self_diag = jnp.diag(self_count)
        stats = RedistributeStats(
            send_counts=send_counts + self_diag,
            recv_counts=recv_counts + self_diag,
            dropped_send=dropped_send.astype(jnp.int32),
            dropped_recv=dropped_recv,
            needed_capacity=needed,
        )
        return out, new_count, stats

    return fn


def shard_redistribute_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """PLANAR multi-device canonical exchange (runs under ``shard_map``).

    The shard_map twin of :func:`vrank_redistribute_planar_fn`: same
    routing (``binning.rank_of_position_planar``), same ``pack_cols`` pack,
    same payload-carrying-sort compaction
    (``pack.planar_compact_with_self``), same capacity/overflow accounting
    — but the V-way transpose is a real ``lax.all_to_all`` over the mesh
    axes, riding ICI. The per-shard state is ``[K, n]`` component-major
    throughout: no narrow-minor ``[n, 3]`` buffer exists on either side of
    the wire (the row-major :func:`shard_redistribute_fn` gathers and
    exchanges ``[R, C, 3]`` buffers, every one stored in TPU's tiled
    T(8,128) layout at 42.7x the logical bytes — the measured 7x per-row
    deficit the planar engines remove, BENCH_CONFIGS.md config 1).

    Signature of the returned fn: ``(fused[K, n], count[1] int32) ->
    (fused_out[K, out_capacity], count_out[1], stats)``; columns beyond
    ``count_out`` are zero. 32-bit fields ride bitcast
    (:func:`..migrate.fuse_fields` semantics, minus the alive row).
    ``fused`` may be float32 or int32; the transport runs on an int32
    bitcast view either way (TPU denormal-flush hazard — see
    :func:`vrank_redistribute_planar_fn`); output dtype matches input.
    """
    R = grid.nranks
    C = capacity
    D = domain.ndim if ndim is None else ndim
    axes = grid.axis_names

    def fn(fused, count):
        if fused.ndim != 2 or fused.shape[0] < D:
            raise ValueError(
                f"fused must be [K>={D}, n] per shard (K rows: {D} "
                f"position components first, then 32-bit fields), got "
                f"{fused.shape}"
            )
        if fused.dtype not in (jnp.float32, jnp.int32):
            raise TypeError(
                f"fused must be float32 or int32, got {fused.dtype}"
            )
        as_f32 = fused.dtype == jnp.float32
        fi = (
            lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
        )
        pos_f = (
            fused[:D]
            if as_f32
            else lax.bitcast_convert_type(fi[:D], jnp.float32)
        )
        n = fused.shape[1]
        me = lax.axis_index(axes).astype(jnp.int32)
        iota = jnp.arange(n, dtype=jnp.int32)
        valid = iota < count[0]
        with traced_span("rd:bin"):
            dest = binning.rank_of_position_planar(
                pos_f, domain, grid, edges=edges
            )
            dest = jnp.where(valid, dest, R).astype(jnp.int32)
            # Self-owned columns stay local (never hit the wire); sentinel
            # R routes both invalid and self columns out of the remote
            # pack.
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, R, dest)
            order, remote_counts, bounds = binning.sorted_dest_counts(
                dest_remote, R
            )
        dropped_send = jnp.sum(jnp.maximum(remote_counts - C, 0))
        send_counts = jnp.minimum(remote_counts, C)
        with traced_span("rd:pack"):
            packed, _ = pack.pack_cols(
                fi, order, bounds[:R], send_counts, R, C
            )  # [K, R*C] int32, dest-major slots
        with traced_span("rd:exchange"):
            recv_counts = lax.all_to_all(
                send_counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
            # The wire: tiled all_to_all splits the lane axis into R
            # chunks of C columns (chunk d -> rank d) and concatenates
            # receives source-major — exactly the [K, R*C] dst-major pool
            # the vrank twin builds with its transpose.
            pool = lax.all_to_all(
                packed, axes, split_axis=1, concat_axis=1, tiled=True
            )
        with traced_span("rd:unpack"):
            out, new_count, dropped_recv = pack.planar_compact_with_self(
                pool, recv_counts, me, is_self, fi, out_capacity
            )
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        self_count = jnp.sum(is_self.astype(jnp.int32))
        self_onehot = (jnp.arange(R, dtype=jnp.int32) == me) * self_count
        stats = RedistributeStats(
            send_counts=(send_counts + self_onehot)[None, :],
            recv_counts=(recv_counts + self_onehot)[None, :],
            dropped_send=dropped_send[None].astype(jnp.int32),
            dropped_recv=dropped_recv[None],
            needed_capacity=jnp.max(remote_counts)[None].astype(jnp.int32),
        )
        return out, new_count[None], stats

    return fn


def shard_redistribute_planar_sharded(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """``shard_map``-wrapped (unjitted) planar exchange — composable under
    an outer jit (the public API fuses its field-bitcast boundary into the
    same program; see :mod:`..api`).

    Global layout: ``fused`` is ``[K, R * n_local]`` component-major,
    sharded on the LANE axis over all mesh axes (x-major, matching rank
    order — shard r owns columns ``[r * n_local, (r + 1) * n_local)``);
    ``count`` is ``[R]`` int32 with one entry per shard. Returns
    ``(fused_out [K, R * out_capacity], count_out [R], stats)``.
    """
    axes = grid.axis_names
    spec_f = P(None, axes)
    spec_c = P(axes)
    fn = shard_redistribute_planar_fn(
        domain, grid, capacity, out_capacity, ndim, edges=edges
    )
    out_specs = (
        spec_f,
        spec_c,
        RedistributeStats(
            *([spec_c] * len(RedistributeStats._fields))
        ),
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec_f, spec_c), out_specs=out_specs
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_planar(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """jit of :func:`shard_redistribute_planar_sharded` (global planar)."""
    return jax.jit(
        shard_redistribute_planar_sharded(
            mesh, domain, grid, capacity, out_capacity, ndim, edges=edges
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_planar_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    ndim: int = None,
    edges=None,
):
    """jit of :func:`vrank_redistribute_planar_fn` ([V, K, n] planar)."""
    return jax.jit(
        vrank_redistribute_planar_fn(
            domain, grid, capacity, out_capacity, ndim, edges=edges
        )
    )


@functools.lru_cache(maxsize=64)
def build_redistribute_vranks(
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    edges=None,
):
    """jit of :func:`vrank_redistribute_fn` (single-device, [V, n, ...])."""
    return jax.jit(
        vrank_redistribute_fn(domain, grid, capacity, out_capacity, edges)
    )


@functools.lru_cache(maxsize=64)
def build_redistribute(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    capacity: int,
    out_capacity: int,
    n_fields: int,
    edges=None,
):
    """jit-compiled global redistribute over ``mesh``.

    Global layout: ``pos`` is ``[R * n_local, D]`` sharded on axis 0 over all
    mesh axes (x-major, matching rank order); ``count`` is ``[R]`` int32 with
    one entry per shard. Returns the same layout with leading dim
    ``R * out_capacity`` plus a :class:`RedistributeStats`.
    """
    axes = grid.axis_names
    spec = P(axes)
    fn = shard_redistribute_fn(domain, grid, capacity, out_capacity, edges)
    in_specs = (spec, spec) + (spec,) * n_fields
    out_specs = (
        (spec, spec)
        + (spec,) * n_fields
        + (RedistributeStats(*([spec] * len(RedistributeStats._fields))),)
    )
    sharded = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return jax.jit(sharded)
