"""Halo / ghost-particle exchange (SURVEY.md C8, §3.4).

Stencil ops (CIC deposit with force interpolation, short-range forces) need
copies of neighbor shards' particles within ``halo_width`` of the subdomain
faces. The reference family does this with extra MPI sends (SURVEY.md C8,
[RECALL] — mount empty); the TPU-native design is the classic 2-passes-per-
axis exchange on the device mesh:

  * per axis, take a snapshot of (own + already-received) particles, select
    the slabs within ``halo_width`` of the hi/lo faces, and ``lax.ppermute``
    each padded slab one step along that mesh axis (+1, then -1);
  * received ghosts participate in *later* axes' passes, so edge and corner
    ghosts propagate in at most ``ndim`` hops with only ``2 * ndim``
    collectives (not 3^ndim - 1 neighbor sends);
  * crossing a periodic wrap shifts the ghost coordinate by ±extent so
    ghost positions are continuous in the receiver's frame;
  * everything is capacity-padded ([pass_capacity] per hop,
    [ghost_capacity] total) with overflow counted and surfaced.

``halo_width`` must not exceed the per-axis subdomain width: one hop per
axis is exactly the single-neighbor-shell guarantee.

Capacities default to sizes derived from the halo-volume fraction
(:func:`default_capacities`): under near-uniform density the expected shell
population is ``n_local * (prod(1 + 2*w_a/cell_w_a) - 1)``, padded by a
headroom factor. Clustered inputs can exceed any static bound — overflow is
counted per shard and returned (never silent), mirroring the redistribute
path's measured-capacity contract.

Two interchangeable engines share the per-slab math (same mask, same
stable pack, same append), differing only in the communication primitive:

  * :func:`build_halo_exchange` — ``shard_map`` over a device mesh,
    ``lax.ppermute`` on the wire (ICI);
  * :func:`build_halo_vranks` — V virtual ranks on ONE device, vmapped
    slabs, the ppermute becomes the grid-axis roll it would perform on the
    wire. Lets a single chip run — and honestly benchmark — the halo at
    any R, exactly like the redistribute's vrank twin.

Round 4 adds the PLANAR twins (:func:`build_halo_planar` /
:func:`build_halo_planar_vranks`): the payload rides ``[K, n]``
component-major int32 (positions bitcast; fields bitcast — the same
bit-pattern-safe transport as the canonical planar engines), selections
pack with a 2-operand key sort + one flat column gather, and appends are
contiguous ``dynamic_update_slice`` blocks instead of row scatters. Same
ghost set, same order, bit-identical values — only the layout differs.
The row-major engines paid 181.7 ns/ghost at config-6 shapes, dominated
by T(8,128) tile padding on every ``[m, 3]`` buffer (BENCH_CONFIGS.md).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from mpi_grid_redistribute_tpu.compat import shard_map

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops.pack import _stable_order, _take_rows, _mask_rows
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib


class HaloResult(NamedTuple):
    """Global ghost buffers: positions [R*ghost_capacity, D] (shifted into
    the receiver's frame across periodic wraps), per-shard ghost counts [R],
    carried fields, and the per-shard overflow counter [R]."""

    ghost_positions: jax.Array
    ghost_count: jax.Array
    ghost_fields: Tuple
    overflow: jax.Array


def _as_per_axis(width, ndim: int) -> Tuple[float, ...]:
    if isinstance(width, (int, float)):
        return (float(width),) * ndim
    t = tuple(float(w) for w in width)
    if len(t) != ndim:
        raise ValueError(f"halo_width must have {ndim} entries, got {len(t)}")
    return t


def _validate_widths(domain: Domain, grid: ProcessGrid, halo_width):
    ndim = domain.ndim
    widths = _as_per_axis(halo_width, ndim)
    cell_w = grid.cell_widths(domain)
    for a in range(ndim):
        if widths[a] < 0:
            raise ValueError(f"halo_width[{a}] must be >= 0")
        if widths[a] > cell_w[a]:
            raise ValueError(
                f"halo_width[{a}]={widths[a]} exceeds subdomain width "
                f"{cell_w[a]}; multi-hop halos are not supported"
            )
    return widths, cell_w


def default_capacities(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    n_local: int,
    headroom: float = 2.0,
) -> Tuple[int, int]:
    """Derived ``(pass_capacity, ghost_capacity)`` for near-uniform density.

    ``n_local`` is the PADDED per-shard row count (``positions.shape[0]
    // R`` — the static buffer size every shard carries), not the valid
    count: capacities must hold whatever the buffers could contain, and
    valid counts are per-shard device values unknown when the static
    program is built. With the default ``headroom=2.0`` the budgets are
    therefore conservative for buffers that are mostly padding — a shard
    whose valid rows are a small fraction of ``n_local`` still gets
    capacities sized from the full padded buffer.

    Per axis the face-shell fraction is ``f_a = w_a / cell_w_a`` per
    direction; a pass along axis ``a`` selects from own rows plus ghosts
    received on earlier axes, so its expected send is
    ``n_local * f_a * prod_{b<a}(1 + 2 f_b)`` and the total expected shell
    population is ``n_local * (prod_a(1 + 2 f_a) - 1)``. Both are padded by
    ``headroom`` (default 2x) and rounded up to a lane-friendly multiple of
    8. Clustered inputs can exceed these bounds — the exchange counts and
    returns ``overflow`` per shard; on a nonzero overflow, rebuild with
    bigger capacities (same contract as the redistribute's measured
    ``needed_capacity``).
    """
    widths, cell_w = _validate_widths(domain, grid, halo_width)
    if n_local <= 0:
        raise ValueError(f"n_local must be positive, got {n_local}")
    f = [w / cw for w, cw in zip(widths, cell_w)]
    pass_cap = 0.0
    grown = 1.0
    for a in range(domain.ndim):
        pass_cap = max(pass_cap, n_local * f[a] * grown)
        grown *= 1.0 + 2.0 * f[a]
    ghost_cap = n_local * (grown - 1.0)

    def pad(x: float) -> int:
        return max(8, int(math.ceil(x * headroom / 8.0)) * 8)

    return pad(pass_cap), pad(ghost_cap)


def _select_for_pass(cand, cand_valid, a, dirn, lo_a, hi_a, w, at_edge,
                     periodic, extent_a, H):
    """Per-slab, per-(axis, direction) outgoing selection.

    Picks the candidate rows within ``w`` of the face, stable-packs the
    first ``H`` into a padded send buffer, applies the periodic frame
    shift, and returns ``(send_tree, send_cnt, overflow_inc)``. Shared by
    the shard_map and vrank engines so their semantics cannot drift.
    """
    pos = cand[0]
    coord = pos[:, a]
    if dirn == 1:
        mask = cand_valid & (coord >= hi_a - w)
    else:
        mask = cand_valid & (coord < lo_a + w)
    if not periodic:
        mask = mask & jnp.logical_not(at_edge)
    cnt = jnp.sum(mask.astype(jnp.int32))
    overflow_inc = jnp.maximum(cnt - H, 0)
    send_cnt = jnp.minimum(cnt, H)
    order = _stable_order(~mask)
    take = _take_rows(order, H)
    slot_valid = jnp.arange(H, dtype=jnp.int32) < send_cnt
    send = jax.tree.map(
        lambda arr: _mask_rows(jnp.take(arr, take, axis=0), slot_valid),
        cand,
    )
    # Periodic wrap: shift the ghost coordinate into the receiver's frame
    # (+1 across the hi wrap -> subtract extent).
    shift = jnp.where(
        at_edge & periodic,
        -jnp.asarray(dirn, pos.dtype) * extent_a,
        jnp.asarray(0, pos.dtype),
    )
    send_pos = send[0].at[:, a].add(jnp.where(slot_valid, shift, 0))
    return (send_pos,) + tuple(send[1:]), send_cnt, overflow_inc


def _append_recv(ghost, gcount, overflow, recv, recv_cnt, H, G):
    """Append a received padded slab to the per-slab ghost buffers."""
    app_valid = jnp.arange(H, dtype=jnp.int32) < recv_cnt
    overflow = overflow + jnp.maximum(gcount + recv_cnt - G, 0)
    idx = jnp.where(app_valid, gcount + jnp.arange(H, dtype=jnp.int32), G)
    ghost = jax.tree.map(
        lambda gh, rc: gh.at[idx].set(rc, mode="drop"), ghost, recv
    )
    return ghost, jnp.minimum(gcount + recv_cnt, G), overflow


def _select_cols_for_pass(cand, cand_valid, a, dirn, lo_a, hi_a, w,
                          at_edge, periodic, extent_a, H):
    """PLANAR per-slab, per-(axis, direction) outgoing selection.

    ``cand`` is ``[K, m]`` int32 transport (position rows bitcast); the
    selected columns are packed with a cheap 2-operand key sort + ONE
    flat column gather of ``H`` columns — the round-3 canonical-engine
    recipe (the row-major :func:`_select_for_pass` gathers whole
    ``[m, 3]`` rows, every one stored 42.7x padded in T(8,128)).
    Returns ``(send [K, H] int32, send_cnt, overflow_inc)``.
    """
    D_row = lax.bitcast_convert_type(cand[a, :], jnp.float32)
    if dirn == 1:
        mask = cand_valid & (D_row >= hi_a - w)
    else:
        mask = cand_valid & (D_row < lo_a + w)
    if not periodic:
        mask = mask & jnp.logical_not(at_edge)
    cnt = jnp.sum(mask.astype(jnp.int32))
    overflow_inc = jnp.maximum(cnt - H, 0)
    send_cnt = jnp.minimum(cnt, H)
    order = _stable_order(jnp.logical_not(mask))  # shared with the
    # row-major twin: ONE copy of the bit-sensitive ordering contract
    take = _take_rows(order, H)  # zero-pads when H > m, like the
    # row-major twin (the padding columns are masked below)
    # Periodic wrap: shift the ghost coordinate into the receiver's frame
    # (+1 across the hi wrap -> subtract extent). One-row f32 surgery.
    shift = jnp.where(
        at_edge & periodic,
        -jnp.asarray(dirn, jnp.float32) * extent_a,
        jnp.asarray(0, jnp.float32),
    )
    send = _banded_send_cols(cand, take, send_cnt, a, shift, H)
    return send, send_cnt, overflow_inc


def _bands_disjoint(domain: Domain, a: int, widths, cell_w) -> bool:
    """True when axis ``a``'s two face bands cannot overlap EVEN AFTER
    f32 threshold rounding. The per-rank thresholds
    ``fl(fl(lo_a + cell_w) - w)`` and ``fl(lo_a + w)`` each carry up to
    ~1.5 ulp of the coordinate magnitude, so at exactly ``2w == cell_w``
    they can land 1 ulp CROSSED — a particle then satisfies both masks,
    and the banded sort would send it in one direction only (review
    round 4, reproduced numerically). Requiring
    ``2w <= cell_w - 4 ulp(max |domain coord|)`` keeps the merged
    single-sort path provably disjoint; anything closer falls back to
    the per-direction two-sort path, which handles overlap correctly."""
    hi_abs = max(
        abs(domain.lo[a]), abs(domain.lo[a] + domain.extent[a])
    )
    margin = 4.0 * 2.0**-23 * max(hi_abs, 1e-30)
    return 2.0 * widths[a] <= cell_w[a] - margin


def _axis_band_order(mask_hi, mask_lo):
    """One packed sort ordering +dir columns first, then -dir, then the
    rest — iota-stable within each band. When the two face bands are
    DISJOINT (``2w <= cell_w``), the first ``cnt_hi`` entries equal
    :func:`ops.pack._stable_order`'s output for ``mask_hi`` and the next
    ``cnt_lo`` equal it for ``mask_lo``, so one sort replaces two
    bit-for-bit (the slots beyond each band are zero-masked by the
    callers either way)."""
    m = mask_hi.shape[0]
    iota = jnp.arange(m, dtype=jnp.int32)
    band = jnp.where(
        mask_hi, 0, jnp.where(mask_lo, 1, 2)
    ).astype(jnp.int32)
    b = max(1, (m - 1).bit_length())
    if b <= 29:  # 2-bit band + b iota bits fit one int32 word
        packed = jax.lax.sort((band << b) | iota, is_stable=False)
        return packed & jnp.int32((1 << b) - 1)
    out = jax.lax.sort((band, iota), num_keys=2, is_stable=False)
    return out[-1]


def _banded_send_cols(cand, order_window, send_cnt, a, slot_shift, H):
    """Build one direction's planar send buffer from an order window:
    gather ``H`` columns, zero-mask beyond ``send_cnt``, apply the
    periodic frame shift on the face coordinate row."""
    slot_valid = jnp.arange(H, dtype=jnp.int32) < send_cnt
    send = jnp.where(
        slot_valid[None, :], jnp.take(cand, order_window, axis=1), 0
    )
    row_a = lax.bitcast_convert_type(send[a, :], jnp.float32)
    row_a = jnp.where(slot_valid, row_a + slot_shift, row_a)
    return jnp.concatenate(
        [
            send[:a],
            lax.bitcast_convert_type(row_a, jnp.int32)[None, :],
            send[a + 1 :],
        ],
        axis=0,
    )


def _select_cols_for_axis(cand, cand_valid, a, lo_a, hi_a, w,
                          at_edge_hi, at_edge_lo, periodic, extent_a, H):
    """PLANAR per-slab selection for BOTH directions of one axis with a
    single banded sort (callers gate on ``2w <= cell_w`` so the bands
    are disjoint; output bits match two :func:`_select_cols_for_pass`
    calls — tested). Returns
    ``(send_hi, cnt_hi, ov_hi, send_lo, cnt_lo, ov_lo)``."""
    D_row = lax.bitcast_convert_type(cand[a, :], jnp.float32)
    mask_hi = cand_valid & (D_row >= hi_a - w)
    mask_lo = cand_valid & (D_row < lo_a + w)
    if not periodic:
        mask_hi = mask_hi & jnp.logical_not(at_edge_hi)
        mask_lo = mask_lo & jnp.logical_not(at_edge_lo)
    cnt_hi_f = jnp.sum(mask_hi.astype(jnp.int32))
    cnt_lo_f = jnp.sum(mask_lo.astype(jnp.int32))
    ov_hi = jnp.maximum(cnt_hi_f - H, 0)
    ov_lo = jnp.maximum(cnt_lo_f - H, 0)
    cnt_hi = jnp.minimum(cnt_hi_f, H)
    cnt_lo = jnp.minimum(cnt_lo_f, H)
    order = _axis_band_order(mask_hi, mask_lo)
    # window [0, H) is the +dir band; [cnt_hi_f, cnt_hi_f + H) the -dir
    # band (zero-pad so the dynamic window never clamps short)
    order_pad = jnp.concatenate(
        [order, jnp.zeros((H,), jnp.int32)]
    )
    take_hi = order_pad[:H]
    take_lo = lax.dynamic_slice(order_pad, (cnt_hi_f,), (H,))
    shift_hi = jnp.where(
        at_edge_hi & periodic,
        -jnp.asarray(1, jnp.float32) * extent_a,
        jnp.asarray(0, jnp.float32),
    )
    shift_lo = jnp.where(
        at_edge_lo & periodic,
        jnp.asarray(1, jnp.float32) * extent_a,
        jnp.asarray(0, jnp.float32),
    )
    send_hi = _banded_send_cols(cand, take_hi, cnt_hi, a, shift_hi, H)
    send_lo = _banded_send_cols(cand, take_lo, cnt_lo, a, shift_lo, H)
    return send_hi, cnt_hi, ov_hi, send_lo, cnt_lo, ov_lo


def _append_recv_cols(ghost, gcount, overflow, recv, recv_cnt, H, G):
    """Append a received planar slab to the ghost buffer — one contiguous
    ``dynamic_update_slice`` (12.9 ns/row measured for contiguous tail
    DUS vs ~76-85 ns/row for scatter; scripts/microbench_layout.py).
    ``ghost`` is ``[K, G + H]``: the ``H``-column scratch tail absorbs
    the block write when the buffer is full, so overflow drops cleanly
    instead of clobbering earlier ghosts; callers slice ``[:, :G]`` at
    the end."""
    overflow = overflow + jnp.maximum(gcount + recv_cnt - G, 0)
    start = jnp.minimum(gcount, G).astype(jnp.int32)
    # zero the recv tail beyond recv_cnt: those columns overwrite ghost
    # slots that the NEXT append will claim, so they must be zero (and
    # are — _select_cols_for_pass zero-masks beyond send_cnt)
    ghost = lax.dynamic_update_slice(ghost, recv, (jnp.int32(0), start))
    return ghost, jnp.minimum(gcount + recv_cnt, G), overflow


def vrank_halo_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
    ndim: int = None,
):
    """PLANAR V-rank halo exchange on ONE device: ``[V, K, n]`` fused state.

    Same 2-passes-per-axis structure, same selection predicate, same
    append order as :func:`vrank_halo_fn` — the ghost SET and ORDER are
    identical — but the payload is carried component-major (``K`` rows:
    ``D`` position components first, then 32-bit fields), so no
    narrow-minor ``[n, 3]`` buffer pays the T(8,128) tile padding, and
    the transport is int32 (bit-pattern-safe on TPU vector units; see
    ``exchange.vrank_redistribute_planar_fn``). Config 6 measured the
    row-major halo at 181.7 ns/ghost — ~25x the migrate engine's per-row
    cost for exactly this layout reason (BENCH_CONFIGS.md row 6).

    Signature: ``(fused [V, K, n], count [V]) ->
    (ghost [V, K, G], gcount [V], overflow [V])``; ``fused`` may be
    float32 or int32 (output matches). Ghost columns beyond
    ``gcount[v]`` are zero.
    """
    widths, cell_w = _validate_widths(domain, grid, halo_width)
    H, G = pass_capacity, ghost_capacity
    V = grid.nranks
    nd = domain.ndim if ndim is None else ndim

    def fn(fused, count):
        if fused.ndim != 3 or fused.shape[0] != V or fused.shape[1] < nd:
            raise ValueError(
                f"fused must be [V={V}, K>={nd}, n], got {fused.shape}"
            )
        as_f32 = fused.dtype == jnp.float32
        fi = (
            lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
        )
        K = fi.shape[1]
        n = fi.shape[2]
        valid = jnp.arange(n, dtype=jnp.int32)[None, :] < count[:, None]
        # scratch tail of H columns absorbs full-buffer appends cleanly
        ghost = jnp.zeros((V, K, G + H), jnp.int32)
        gcount = jnp.zeros((V,), jnp.int32)
        overflow = jnp.zeros((V,), jnp.int32)
        ranks = jnp.arange(V, dtype=jnp.int32)
        strides = grid.strides

        for a in range(nd):
            g = grid.shape[a]
            w = jnp.asarray(widths[a], jnp.float32)
            extent_a = jnp.asarray(domain.extent[a], jnp.float32)
            coord_idx = (ranks // strides[a]) % g
            lo_a = (
                jnp.asarray(domain.lo[a], jnp.float32)
                + coord_idx.astype(jnp.float32)
                * jnp.asarray(cell_w[a], jnp.float32)
            )
            hi_a = lo_a + jnp.asarray(cell_w[a], jnp.float32)

            # snapshot before this axis's passes (ghosts received on
            # earlier axes participate; same-axis bounce is impossible).
            # STATIC candidate window: before axis a only 2a appends
            # have happened, each clipped at H columns, so ghost columns
            # past min(G, 2aH) are provably invalid — axis 0 sorts over
            # no ghost columns at all (candidate tightening measured
            # ~36% of the sort+predicate volume at config-6 shape)
            Wa = min(G, 2 * a * H)
            cand = jnp.concatenate([fi, ghost[:, :, :Wa]], axis=2)
            cand_valid = jnp.concatenate(
                [
                    valid,
                    jnp.arange(Wa, dtype=jnp.int32)[None, :]
                    < gcount[:, None],
                ],
                axis=1,
            )

            incoming = []
            if _bands_disjoint(domain, a, widths, cell_w):
                # disjoint face bands: ONE banded sort serves both
                # directions (bit-identical sends, half the sort volume)
                at_hi = coord_idx == (g - 1)
                at_lo = coord_idx == 0
                s_hi, c_hi, o_hi, s_lo, c_lo, o_lo = jax.vmap(
                    lambda c_v, cv_v, lo_v, hi_v, eh_v, el_v:
                    _select_cols_for_axis(
                        c_v, cv_v, a, lo_v, hi_v, w, eh_v, el_v,
                        domain.periodic[a], extent_a, H,
                    )
                )(cand, cand_valid, lo_a, hi_a, at_hi, at_lo)
                overflow = overflow + o_hi + o_lo
                sends = [(1, s_hi, c_hi), (-1, s_lo, c_lo)]
            else:
                sends = []
                for dirn in (1, -1):
                    at_edge = coord_idx == (g - 1 if dirn == 1 else 0)
                    send, send_cnt, ov = jax.vmap(
                        lambda c_v, cv_v, lo_v, hi_v, e_v:
                        _select_cols_for_pass(
                            c_v, cv_v, a, dirn, lo_v, hi_v, w, e_v,
                            domain.periodic[a], extent_a, H,
                        )
                    )(cand, cand_valid, lo_a, hi_a, at_edge)
                    overflow = overflow + ov
                    sends.append((dirn, send, send_cnt))
            for dirn, send, send_cnt in sends:
                # the wire, as a roll on the grid-shaped vrank axis
                recv = jnp.roll(
                    send.reshape(grid.shape + send.shape[1:]), dirn, axis=a
                ).reshape(send.shape)
                recv_cnt = jnp.roll(
                    send_cnt.reshape(grid.shape), dirn, axis=a
                ).reshape((V,))
                incoming.append((recv, recv_cnt))

            for recv, recv_cnt in incoming:
                ghost, gcount, overflow = jax.vmap(
                    lambda gh_v, gc_v, ov_v, rc_v, rcnt_v: _append_recv_cols(
                        gh_v, gc_v, ov_v, rc_v, rcnt_v, H, G
                    )
                )(ghost, gcount, overflow, recv, recv_cnt)

        out = ghost[:, :, :G]
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        return out, gcount, overflow

    return fn


def shard_halo_planar_fn(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
    ndim: int = None,
):
    """PLANAR per-shard halo exchange (runs under ``shard_map``).

    The multi-device twin of :func:`vrank_halo_planar_fn`: identical
    selection/append helpers, ``lax.ppermute`` on the wire. Signature:
    ``(fused [K, n], count [1]) -> (ghost [K, G], gcount [1],
    overflow [1])``.
    """
    widths, cell_w = _validate_widths(domain, grid, halo_width)
    H, G = pass_capacity, ghost_capacity
    nd = domain.ndim if ndim is None else ndim

    def fn(fused, count):
        if fused.ndim != 2 or fused.shape[0] < nd:
            raise ValueError(
                f"fused must be [K>={nd}, n] per shard, got {fused.shape}"
            )
        as_f32 = fused.dtype == jnp.float32
        fi = (
            lax.bitcast_convert_type(fused, jnp.int32) if as_f32 else fused
        )
        n = fi.shape[1]
        valid = jnp.arange(n, dtype=jnp.int32) < count[0]
        ghost = jnp.zeros((fi.shape[0], G + H), jnp.int32)
        gcount = jnp.zeros((), jnp.int32)
        overflow = jnp.zeros((), jnp.int32)

        for a, name in enumerate(grid.axis_names[:nd]):
            g = grid.shape[a]
            w = jnp.asarray(widths[a], jnp.float32)
            extent_a = jnp.asarray(domain.extent[a], jnp.float32)
            coord_idx = lax.axis_index(name).astype(jnp.int32)
            lo_a = (
                jnp.asarray(domain.lo[a], jnp.float32)
                + coord_idx.astype(jnp.float32)
                * jnp.asarray(cell_w[a], jnp.float32)
            )
            hi_a = lo_a + jnp.asarray(cell_w[a], jnp.float32)

            # static candidate window (see vrank twin): before axis a at
            # most 2aH ghost columns can be valid
            Wa = min(G, 2 * a * H)
            cand = jnp.concatenate([fi, ghost[:, :Wa]], axis=1)
            cand_valid = jnp.concatenate(
                [valid, jnp.arange(Wa, dtype=jnp.int32) < gcount]
            )

            incoming = []
            if _bands_disjoint(domain, a, widths, cell_w):
                at_hi = coord_idx == (g - 1)
                at_lo = coord_idx == 0
                s_hi, c_hi, o_hi, s_lo, c_lo, o_lo = _select_cols_for_axis(
                    cand, cand_valid, a, lo_a, hi_a, w, at_hi, at_lo,
                    domain.periodic[a], extent_a, H,
                )
                overflow = overflow + o_hi + o_lo
                sends = [(1, s_hi, c_hi), (-1, s_lo, c_lo)]
            else:
                sends = []
                for dirn in (1, -1):
                    at_edge = coord_idx == (g - 1 if dirn == 1 else 0)
                    send, send_cnt, ov = _select_cols_for_pass(
                        cand, cand_valid, a, dirn, lo_a, hi_a, w, at_edge,
                        domain.periodic[a], extent_a, H,
                    )
                    overflow = overflow + ov
                    sends.append((dirn, send, send_cnt))
            for dirn, send, send_cnt in sends:
                perm = [(i, (i + dirn) % g) for i in range(g)]
                recv = lax.ppermute(send, name, perm)
                recv_cnt = lax.ppermute(send_cnt, name, perm)
                incoming.append((recv, recv_cnt))

            for recv, recv_cnt in incoming:
                ghost, gcount, overflow = _append_recv_cols(
                    ghost, gcount, overflow, recv, recv_cnt, H, G
                )

        out = ghost[:, :G]
        if as_f32:
            out = lax.bitcast_convert_type(out, jnp.float32)
        return out, gcount[None], overflow[None]

    return fn


@functools.lru_cache(maxsize=64)
def build_halo_planar_vranks(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
):
    """jit of :func:`vrank_halo_planar_fn` (single-device, [V, K, n])."""
    widths = _as_per_axis(halo_width, domain.ndim)
    return jax.jit(
        vrank_halo_planar_fn(
            domain, grid, widths, pass_capacity, ghost_capacity
        )
    )


@functools.lru_cache(maxsize=64)
def build_halo_planar(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
):
    """jit-compiled global PLANAR halo exchange over ``mesh``.

    Global layout: ``fused`` ``[K, R * n_local]`` lane-sharded over the
    grid axes (like ``exchange.build_redistribute_planar``); returns
    ``(ghost [K, R * G], gcount [R], overflow [R])``.
    """
    mesh_lib.validate_mesh_for_grid(mesh, grid)
    widths = _as_per_axis(halo_width, domain.ndim)
    axes = grid.axis_names
    spec_f = P(None, axes)
    spec_c = P(axes)
    fn = shard_halo_planar_fn(
        domain, grid, widths, pass_capacity, ghost_capacity
    )
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(spec_f, spec_c),
            out_specs=(spec_f, spec_c, spec_c),
        )
    )


def shard_halo_fn(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
):
    """Per-shard halo exchange closure (runs under ``shard_map``).

    Signature: ``(pos[N,D], count[1], *fields) ->
    (ghost_pos[G,D], ghost_count[1], *ghost_fields, overflow[1])``.
    """
    widths, cell_w = _validate_widths(domain, grid, halo_width)
    H, G = pass_capacity, ghost_capacity

    def fn(pos, count, *fields):
        n = pos.shape[0]
        valid = jnp.arange(n, dtype=jnp.int32) < count[0]
        arrays = (pos,) + tuple(fields)
        ghost = jax.tree.map(
            lambda a: jnp.zeros((G,) + a.shape[1:], a.dtype), arrays
        )
        gcount = jnp.zeros((), jnp.int32)
        overflow = jnp.zeros((), jnp.int32)

        for a, name in enumerate(grid.axis_names):
            g = grid.shape[a]
            w = jnp.asarray(widths[a], pos.dtype)
            extent_a = jnp.asarray(domain.extent[a], pos.dtype)
            coord_idx = lax.axis_index(name).astype(jnp.int32)
            lo_a = (
                jnp.asarray(domain.lo[a], pos.dtype)
                + coord_idx.astype(pos.dtype) * jnp.asarray(cell_w[a], pos.dtype)
            )
            hi_a = lo_a + jnp.asarray(cell_w[a], pos.dtype)

            # Snapshot BEFORE this axis's passes: both directions select from
            # it, so a ghost just received from -x is never bounced back +x.
            cand = jax.tree.map(
                lambda own, gh: jnp.concatenate([own, gh], axis=0),
                arrays,
                ghost,
            )
            cand_valid = jnp.concatenate(
                [valid, jnp.arange(G, dtype=jnp.int32) < gcount]
            )

            incoming = []
            for dirn in (1, -1):
                at_edge = coord_idx == (g - 1 if dirn == 1 else 0)
                send, send_cnt, ov = _select_for_pass(
                    cand, cand_valid, a, dirn, lo_a, hi_a, w, at_edge,
                    domain.periodic[a], extent_a, H,
                )
                overflow = overflow + ov
                perm = [(i, (i + dirn) % g) for i in range(g)]
                recv = jax.tree.map(
                    lambda arr: lax.ppermute(arr, name, perm), send
                )
                recv_cnt = lax.ppermute(send_cnt, name, perm)
                incoming.append((recv, recv_cnt))

            for recv, recv_cnt in incoming:
                ghost, gcount, overflow = _append_recv(
                    ghost, gcount, overflow, recv, recv_cnt, H, G
                )

        return (
            (ghost[0], gcount[None])
            + tuple(ghost[1:])
            + (overflow[None],)
        )

    return fn


def vrank_halo_fn(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
):
    """V-rank halo exchange on ONE device (virtual ranks, vmapped).

    Semantically identical to :func:`shard_halo_fn` over a V-way mesh —
    the per-slab selection, frame shift, and append are literally the same
    helpers — but the ranks are vmapped slabs on one device and each
    ``lax.ppermute`` becomes the roll along the row-major grid axis it
    would perform on the wire (receiver ``j`` gets sender ``j - dirn``,
    i.e. ``jnp.roll(send, +dirn, axis=a)`` on the grid-shaped view).

    Signature: ``(pos[V, n, D], count[V], *fields[V, n, ...]) ->
    (ghost_pos[V, G, D], ghost_count[V], *ghost_fields, overflow[V])``.
    """
    widths, cell_w = _validate_widths(domain, grid, halo_width)
    H, G = pass_capacity, ghost_capacity
    V = grid.nranks
    ndim = domain.ndim

    def fn(pos, count, *fields):
        n = pos.shape[1]
        arrays = (pos,) + tuple(fields)
        valid = jnp.arange(n, dtype=jnp.int32)[None, :] < count[:, None]
        ghost = jax.tree.map(
            lambda a: jnp.zeros((V, G) + a.shape[2:], a.dtype), arrays
        )
        gcount = jnp.zeros((V,), jnp.int32)
        overflow = jnp.zeros((V,), jnp.int32)
        ranks = jnp.arange(V, dtype=jnp.int32)
        strides = grid.strides

        for a in range(ndim):
            g = grid.shape[a]
            w = jnp.asarray(widths[a], pos.dtype)
            extent_a = jnp.asarray(domain.extent[a], pos.dtype)
            coord_idx = (ranks // strides[a]) % g  # row-major cell coords
            lo_a = (
                jnp.asarray(domain.lo[a], pos.dtype)
                + coord_idx.astype(pos.dtype)
                * jnp.asarray(cell_w[a], pos.dtype)
            )
            hi_a = lo_a + jnp.asarray(cell_w[a], pos.dtype)

            cand = jax.tree.map(
                lambda own, gh: jnp.concatenate([own, gh], axis=1),
                arrays,
                ghost,
            )
            cand_valid = jnp.concatenate(
                [valid, jnp.arange(G, dtype=jnp.int32)[None, :] < gcount[:, None]],
                axis=1,
            )

            incoming = []
            for dirn in (1, -1):
                at_edge = coord_idx == (g - 1 if dirn == 1 else 0)
                send, send_cnt, ov = jax.vmap(
                    lambda cand_v, cv_v, lo_v, hi_v, edge_v: _select_for_pass(
                        cand_v, cv_v, a, dirn, lo_v, hi_v, w, edge_v,
                        domain.periodic[a], extent_a, H,
                    )
                )(cand, cand_valid, lo_a, hi_a, at_edge)
                overflow = overflow + ov
                # the wire, as a roll on the grid-shaped vrank axis:
                # receiver j gets sender j - dirn along axis a
                recv = jax.tree.map(
                    lambda arr: jnp.roll(
                        arr.reshape(grid.shape + arr.shape[1:]), dirn, axis=a
                    ).reshape(arr.shape),
                    send,
                )
                recv_cnt = jnp.roll(
                    send_cnt.reshape(grid.shape), dirn, axis=a
                ).reshape((V,))
                incoming.append((recv, recv_cnt))

            for recv, recv_cnt in incoming:
                ghost, gcount, overflow = jax.vmap(
                    lambda gh_v, gc_v, ov_v, rc_v, rcnt_v: _append_recv(
                        gh_v, gc_v, ov_v, rc_v, rcnt_v, H, G
                    )
                )(ghost, gcount, overflow, recv, recv_cnt)

        return (ghost[0], gcount) + tuple(ghost[1:]) + (overflow,)

    return fn


def build_halo_vranks(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
):
    """jit of :func:`vrank_halo_fn` (single-device, [V, n, ...] slabs)."""
    # normalize the width to a hashable tuple so per-axis lists hit the cache
    widths = _as_per_axis(halo_width, domain.ndim)
    return _build_halo_vranks_cached(
        domain, grid, widths, pass_capacity, ghost_capacity
    )


@functools.lru_cache(maxsize=64)
def _build_halo_vranks_cached(
    domain: Domain,
    grid: ProcessGrid,
    widths: Tuple[float, ...],
    pass_capacity: int,
    ghost_capacity: int,
):
    return jax.jit(
        vrank_halo_fn(domain, grid, widths, pass_capacity, ghost_capacity)
    )


def build_halo_exchange(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int | None = None,
    ghost_capacity: int | None = None,
    n_fields: int = 0,
    headroom: float = 2.0,
):
    """jit-compiled global halo exchange over ``mesh``.

    Global layout matches the redistribute: ``pos`` [R*n_local, D] /
    ``count`` [R] sharded over the grid axes; returns a :class:`HaloResult`.

    ``pass_capacity`` / ``ghost_capacity`` default to
    :func:`default_capacities` sized from each call's per-shard row count
    (one cached compile per distinct size, LRU-bounded at 16 sizes —
    evicting an entry drops its compiled executable, so a long-lived
    caller cycling through MANY distinct input sizes recompiles on
    revisit; pass explicit ints to pin ONE compile for every size).
    Overflow past either capacity is counted per shard in
    ``HaloResult.overflow``.
    """
    mesh_lib.validate_mesh_for_grid(mesh, grid)
    _validate_widths(domain, grid, halo_width)
    spec = P(grid.axis_names)
    from collections import OrderedDict

    built = OrderedDict()  # n_local -> jitted fn, LRU-bounded
    max_builds = 16

    def _build(n_local: int):
        pc, gc = pass_capacity, ghost_capacity
        if pc is None or gc is None:
            dpc, dgc = default_capacities(
                domain, grid, halo_width, n_local, headroom
            )
            pc = dpc if pc is None else pc
            gc = dgc if gc is None else gc
        fn = shard_halo_fn(domain, grid, halo_width, pc, gc)
        sharded = shard_map(
            fn,
            mesh=mesh,
            in_specs=(spec, spec) + (spec,) * n_fields,
            out_specs=(spec, spec) + (spec,) * n_fields + (spec,),
        )
        return jax.jit(sharded)

    def wrapped(pos, count, *fields):
        # capacities pinned => one build serves every input size
        key = (
            pos.shape[0] // grid.nranks
            if pass_capacity is None or ghost_capacity is None
            else 0
        )
        if key in built:
            built.move_to_end(key)
        else:
            built[key] = _build(key)
            if len(built) > max_builds:
                built.popitem(last=False)
        out = built[key](pos, count, *fields)
        return HaloResult(out[0], out[1], tuple(out[2:-1]), out[-1])

    return wrapped
