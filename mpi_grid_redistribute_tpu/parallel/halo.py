"""Halo / ghost-particle exchange (SURVEY.md C8, §3.4).

Stencil ops (CIC deposit with force interpolation, short-range forces) need
copies of neighbor shards' particles within ``halo_width`` of the subdomain
faces. The reference family does this with extra MPI sends (SURVEY.md C8,
[RECALL] — mount empty); the TPU-native design is the classic 2-passes-per-
axis exchange on the device mesh:

  * per axis, take a snapshot of (own + already-received) particles, select
    the slabs within ``halo_width`` of the hi/lo faces, and ``lax.ppermute``
    each padded slab one step along that mesh axis (+1, then -1);
  * received ghosts participate in *later* axes' passes, so edge and corner
    ghosts propagate in at most ``ndim`` hops with only ``2 * ndim``
    collectives (not 3^ndim - 1 neighbor sends);
  * crossing a periodic wrap shifts the ghost coordinate by ±extent so
    ghost positions are continuous in the receiver's frame;
  * everything is capacity-padded ([pass_capacity] per hop,
    [ghost_capacity] total) with overflow counted and surfaced.

``halo_width`` must not exceed the per-axis subdomain width: one hop per
axis is exactly the single-neighbor-shell guarantee.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops.pack import _stable_order, _take_rows, _mask_rows
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib


class HaloResult(NamedTuple):
    """Global ghost buffers: positions [R*ghost_capacity, D] (shifted into
    the receiver's frame across periodic wraps), per-shard ghost counts [R],
    carried fields, and the per-shard overflow counter [R]."""

    ghost_positions: jax.Array
    ghost_count: jax.Array
    ghost_fields: Tuple
    overflow: jax.Array


def _as_per_axis(width, ndim: int) -> Tuple[float, ...]:
    if isinstance(width, (int, float)):
        return (float(width),) * ndim
    t = tuple(float(w) for w in width)
    if len(t) != ndim:
        raise ValueError(f"halo_width must have {ndim} entries, got {len(t)}")
    return t


def shard_halo_fn(
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
):
    """Per-shard halo exchange closure (runs under ``shard_map``).

    Signature: ``(pos[N,D], count[1], *fields) ->
    (ghost_pos[G,D], ghost_count[1], *ghost_fields, overflow[1])``.
    """
    ndim = domain.ndim
    widths = _as_per_axis(halo_width, ndim)
    cell_w = grid.cell_widths(domain)
    for a in range(ndim):
        if widths[a] < 0:
            raise ValueError(f"halo_width[{a}] must be >= 0")
        if widths[a] > cell_w[a]:
            raise ValueError(
                f"halo_width[{a}]={widths[a]} exceeds subdomain width "
                f"{cell_w[a]}; multi-hop halos are not supported"
            )
    H, G = pass_capacity, ghost_capacity

    def fn(pos, count, *fields):
        n = pos.shape[0]
        valid = jnp.arange(n, dtype=jnp.int32) < count[0]
        arrays = (pos,) + tuple(fields)
        ghost = jax.tree.map(
            lambda a: jnp.zeros((G,) + a.shape[1:], a.dtype), arrays
        )
        gcount = jnp.zeros((), jnp.int32)
        overflow = jnp.zeros((), jnp.int32)

        for a, name in enumerate(grid.axis_names):
            g = grid.shape[a]
            w = jnp.asarray(widths[a], pos.dtype)
            extent_a = jnp.asarray(domain.extent[a], pos.dtype)
            coord_idx = lax.axis_index(name).astype(jnp.int32)
            lo_a = (
                jnp.asarray(domain.lo[a], pos.dtype)
                + coord_idx.astype(pos.dtype) * jnp.asarray(cell_w[a], pos.dtype)
            )
            hi_a = lo_a + jnp.asarray(cell_w[a], pos.dtype)

            # Snapshot BEFORE this axis's passes: both directions select from
            # it, so a ghost just received from -x is never bounced back +x.
            cand = jax.tree.map(
                lambda own, gh: jnp.concatenate([own, gh], axis=0),
                arrays,
                ghost,
            )
            cand_valid = jnp.concatenate(
                [valid, jnp.arange(G, dtype=jnp.int32) < gcount]
            )
            coord = cand[0][:, a]

            incoming = []
            for dirn in (1, -1):
                if dirn == 1:
                    mask = cand_valid & (coord >= hi_a - w)
                    at_edge = coord_idx == g - 1
                else:
                    mask = cand_valid & (coord < lo_a + w)
                    at_edge = coord_idx == 0
                if not domain.periodic[a]:
                    mask = mask & jnp.logical_not(at_edge)
                cnt = jnp.sum(mask.astype(jnp.int32))
                overflow = overflow + jnp.maximum(cnt - H, 0)
                send_cnt = jnp.minimum(cnt, H)
                order = _stable_order(~mask)
                take = _take_rows(order, H)
                slot_valid = jnp.arange(H, dtype=jnp.int32) < send_cnt
                send = jax.tree.map(
                    lambda arr: _mask_rows(
                        jnp.take(arr, take, axis=0), slot_valid
                    ),
                    cand,
                )
                # Periodic wrap: shift the ghost coordinate into the
                # receiver's frame (+1 across hi wrap -> subtract extent).
                shift = jnp.where(
                    at_edge & domain.periodic[a],
                    -jnp.asarray(dirn, pos.dtype) * extent_a,
                    jnp.asarray(0, pos.dtype),
                )
                send_pos = send[0].at[:, a].add(
                    jnp.where(slot_valid, shift, 0)
                )
                send = (send_pos,) + tuple(send[1:])
                perm = [(i, (i + dirn) % g) for i in range(g)]
                recv = jax.tree.map(
                    lambda arr: lax.ppermute(arr, name, perm), send
                )
                recv_cnt = lax.ppermute(send_cnt, name, perm)
                incoming.append((recv, recv_cnt))

            for recv, recv_cnt in incoming:
                app_valid = jnp.arange(H, dtype=jnp.int32) < recv_cnt
                overflow = overflow + jnp.maximum(gcount + recv_cnt - G, 0)
                idx = jnp.where(
                    app_valid, gcount + jnp.arange(H, dtype=jnp.int32), G
                )
                ghost = jax.tree.map(
                    lambda gh, rc: gh.at[idx].set(rc, mode="drop"),
                    ghost,
                    recv,
                )
                gcount = jnp.minimum(gcount + recv_cnt, G)

        return (
            (ghost[0], gcount[None])
            + tuple(ghost[1:])
            + (overflow[None],)
        )

    return fn


def build_halo_exchange(
    mesh: Mesh,
    domain: Domain,
    grid: ProcessGrid,
    halo_width,
    pass_capacity: int,
    ghost_capacity: int,
    n_fields: int = 0,
):
    """jit-compiled global halo exchange over ``mesh``.

    Global layout matches the redistribute: ``pos`` [R*n_local, D] /
    ``count`` [R] sharded over the grid axes; returns a :class:`HaloResult`.
    """
    mesh_lib.validate_mesh_for_grid(mesh, grid)
    spec = P(grid.axis_names)
    fn = shard_halo_fn(domain, grid, halo_width, pass_capacity, ghost_capacity)
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec) + (spec,) * n_fields,
        out_specs=(spec, spec) + (spec,) * n_fields + (spec,),
    )
    jitted = jax.jit(sharded)

    def wrapped(pos, count, *fields):
        out = jitted(pos, count, *fields)
        return HaloResult(out[0], out[1], tuple(out[2:-1]), out[-1])

    return wrapped
