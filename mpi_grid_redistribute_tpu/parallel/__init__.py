"""Mesh construction, collective exchange, halo passes."""
