"""Device-mesh construction mirroring the Cartesian process grid.

The reference's process topology is an MPI Cartesian communicator
(SURVEY.md C1/§2 — mount empty, [DRIVER] spec); the TPU-native equivalent is
a ``jax.sharding.Mesh`` whose axes are the grid axes, so rank r of the grid
*is* device r of the mesh and XLA's ``all_to_all`` over the flattened mesh
axes reproduces the MPI rank ordering (row-major, x-major first).
"""

from __future__ import annotations

import functools
import itertools
import math
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from mpi_grid_redistribute_tpu.domain import ProcessGrid


def make_mesh(grid: ProcessGrid, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh shaped like ``grid`` from ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    need = grid.nranks
    if len(devices) < need:
        raise ValueError(
            f"grid {grid.shape} needs {need} devices, only "
            f"{len(devices)} available"
        )
    arr = np.asarray(devices[:need], dtype=object).reshape(grid.shape)
    return Mesh(arr, grid.axis_names)


def near_cubic_shape(n: int, ndim: int = 3) -> Tuple[int, ...]:
    """Factor ``n`` ranks into an ``ndim``-axis grid as close to cubic as
    possible (largest prime factors spread round-robin). Used when the user
    gives a device count instead of an explicit grid shape."""
    if n < 1:
        raise ValueError("need at least one rank")
    factors = []
    m = n
    d = 2
    while d * d <= m:
        while m % d == 0:
            factors.append(d)
            m //= d
        d += 1
    if m > 1:
        factors.append(m)
    shape = [1] * ndim
    for f in sorted(factors, reverse=True):
        shape[int(np.argmin(shape))] *= f
    return tuple(sorted(shape, reverse=True))


def shrink_shape(shape: Sequence[int]) -> Tuple[int, ...]:
    """One elastic-restart shrink step: halve the largest axis.

    The service supervisor's mesh-shrink policy and the device-loss
    restore path both walk grid shapes DOWN this ladder — deterministic
    (largest extent, lowest axis index on ties, ``extent // 2``), so a
    journaled ``reshard`` event's old/new shapes are reproducible from
    the policy alone. A shape that cannot shrink (all axes 1) is
    returned unchanged; callers treat ``shrink_shape(s) == s`` as "no
    smaller mesh exists".
    """
    shape = tuple(int(x) for x in shape)
    if any(x < 1 for x in shape):
        raise ValueError(f"grid shape must be positive, got {shape}")
    if all(x == 1 for x in shape):
        return shape
    axis = max(range(len(shape)), key=lambda a: (shape[a], -a))
    return shape[:axis] + (max(1, shape[axis] // 2),) + shape[axis + 1:]


def shrink_to_fit(shape: Sequence[int], max_devices: int) -> Tuple[int, ...]:
    """Smallest number of :func:`shrink_shape` steps that fits ``shape``
    onto ``max_devices`` vranks — the restore-time answer to "the mesh
    now reports M < R devices". Raises when even the 1-vrank grid does
    not fit (``max_devices < 1``)."""
    if max_devices < 1:
        raise ValueError(
            f"cannot fit a grid onto {max_devices} devices"
        )
    shape = tuple(int(x) for x in shape)
    while math.prod(shape) > max_devices:
        smaller = shrink_shape(shape)
        if smaller == shape:  # unreachable: prod((1,..)) == 1 <= max
            break
        shape = smaller
    return shape


def initialize_distributed(**kwargs) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` passthrough.

    Where the reference relies on ``mpirun`` to spawn and wire R processes
    (SURVEY.md §3.1 "MPI already launched"), a multi-host TPU job runs one
    process per host and calls this once before any device use; coordinator
    address / process ids come from the TPU pod metadata automatically, or
    from the standard kwargs (coordinator_address, num_processes,
    process_id). Safe to call on a single host (no-op failure is raised by
    JAX only when misconfigured).
    """
    import jax

    jax.distributed.initialize(**kwargs)


def _validate_dcn_shape(
    grid: ProcessGrid, dcn_shape: Optional[Sequence[int]]
) -> Tuple[int, ...]:
    """Shared dcn-shape validation of :func:`make_hybrid_mesh` and
    :class:`HierarchicalMesh`: per-axis pod counts must match the grid's
    ndim and divide each grid extent. ``None`` means all-ones (flat)."""
    if dcn_shape is None:
        dcn_shape = (1,) * grid.ndim
    dcn_shape = tuple(int(d) for d in dcn_shape)
    if len(dcn_shape) != grid.ndim:
        raise ValueError(
            f"dcn_shape must have {grid.ndim} axes, got {dcn_shape}"
        )
    for a, (g, d) in enumerate(zip(grid.shape, dcn_shape)):
        if d < 1:
            raise ValueError(
                f"axis {a}: dcn factor must be >= 1, got {d}"
            )
        if g % d:
            raise ValueError(
                f"axis {a}: grid extent {g} not divisible by dcn {d}"
            )
    return dcn_shape


def make_hybrid_mesh(
    grid: ProcessGrid, dcn_shape: Optional[Sequence[int]] = None
) -> Mesh:
    """Mesh for multi-slice / multi-host jobs: ICI inside a slice, DCN
    across slices.

    ``dcn_shape[a]`` is how many slices the grid axis ``a`` spans (1 =
    axis stays inside a slice). Collectives along intra-slice axes ride
    ICI; only axes split across slices touch DCN — lay out the grid so the
    high-traffic axes stay intra-slice (scaling-book recipe). With
    ``dcn_shape=None`` or all-ones this reduces to :func:`make_mesh` with
    XLA's bandwidth-aware device ordering.
    """
    from jax.experimental import mesh_utils

    dcn_shape = _validate_dcn_shape(grid, dcn_shape)
    if all(d == 1 for d in dcn_shape):
        devices = mesh_utils.create_device_mesh(grid.shape)
    else:
        ici = tuple(g // d for g, d in zip(grid.shape, dcn_shape))
        devices = mesh_utils.create_hybrid_device_mesh(ici, dcn_shape)
    return Mesh(devices, grid.axis_names)


class HierarchicalMesh:
    """Two-level (ICI-inside, DCN-across) view of a process grid.

    ``dcn_shape[a]`` splits grid axis ``a`` into ``d_a`` pods of
    ``g_a // d_a`` ranks each. The *expanded* mesh interleaves a
    ``dcn_<name>`` axis (extent ``d_a``) in front of each split grid
    axis (extent ``g_a // d_a``), so the row-major flat index over the
    expanded axes **equals the grid rank**:

    ``cell_a = pod_a * ici_a + local_a`` and row-major interleaving
    compose exactly — ``lax.axis_index(axis_names)`` inside a
    ``shard_map`` over :meth:`build_mesh` is the grid rank, any
    collective over ALL expanded axes is bit-identical to the same
    collective on the flat mesh, ``lax.axis_index(dcn_axes)`` is the
    pod id and ``lax.axis_index(ici_axes)`` the pod-local rank.

    Static routing tables (numpy, trace-time):

    * ``pod_of [R]`` / ``local_of [R]`` — pod id and pod-local flat
      index of each grid rank;
    * ``rank_table [n_pods, pod_size]`` — grid rank of pod-local slot
      ``l`` in pod ``p`` (ascending in ``l`` for fixed ``p``, which is
      what lets the DCN mirror reconstruct block segmentation from
      per-local-destination counts alone);
    * ``local_grid`` — a :class:`ProcessGrid` over the pod's ICI shape,
      feeding :func:`neighbor_tables` for the intra-pod stencil.
    """

    def __init__(
        self, grid: ProcessGrid, dcn_shape: Optional[Sequence[int]] = None
    ):
        self.grid = grid
        self.dcn_shape = _validate_dcn_shape(grid, dcn_shape)
        self.ici_shape = tuple(
            g // d for g, d in zip(grid.shape, self.dcn_shape)
        )
        self.n_pods = math.prod(self.dcn_shape)
        self.pod_size = math.prod(self.ici_shape)
        names = []
        sizes = []
        dcn_axes = []
        for name, g, d in zip(grid.axis_names, grid.shape, self.dcn_shape):
            if d > 1:
                names.append("dcn_" + name)
                sizes.append(d)
                dcn_axes.append("dcn_" + name)
            names.append(name)
            sizes.append(g // d)
        self.axis_names = tuple(names)
        self.axis_sizes = tuple(sizes)
        self.dcn_axes = tuple(dcn_axes)
        self.ici_axes = tuple(grid.axis_names)
        self.local_grid = ProcessGrid(self.ici_shape)
        R = grid.nranks
        pod_of = np.zeros(R, dtype=np.int32)
        local_of = np.zeros(R, dtype=np.int32)
        rank_table = np.zeros((self.n_pods, self.pod_size), dtype=np.int32)
        for r in range(R):
            cell = grid.cell_of_rank(r)
            p = 0
            l = 0
            for a in range(grid.ndim):
                p = p * self.dcn_shape[a] + cell[a] // self.ici_shape[a]
                l = l * self.ici_shape[a] + cell[a] % self.ici_shape[a]
            pod_of[r] = p
            local_of[r] = l
            rank_table[p, l] = r
        self.pod_of = pod_of
        self.local_of = local_of
        self.rank_table = rank_table

    def local_periodic(self, periodic: Sequence[bool]) -> Tuple[bool, ...]:
        """Periodicity of the pod-local grid: a wrapped axis stays
        periodic inside the pod only when the pod spans the whole axis
        (``d_a == 1``); split axes wrap across pods, which the cross
        stage handles, so the local stencil must not."""
        return tuple(
            bool(p) and d == 1 for p, d in zip(periodic, self.dcn_shape)
        )

    def build_mesh(self, devices: Optional[Sequence] = None) -> Mesh:
        """Expanded-axes ``Mesh``. Device r of the flat layout lands at
        expanded coordinates whose row-major flat index is r, so the
        hybrid ICI/DCN placement of :func:`make_hybrid_mesh` carries
        over by pure reshape (dcn digits are the slow factors on both
        sides). On backends without slice topology (CPU) falls back to
        the plain rank-ordered layout."""
        if devices is None:
            if any(d > 1 for d in self.dcn_shape):
                try:
                    arr = make_hybrid_mesh(self.grid, self.dcn_shape).devices
                except ValueError:
                    arr = make_mesh(self.grid).devices
            else:
                arr = make_mesh(self.grid).devices
        else:
            if len(devices) < self.grid.nranks:
                raise ValueError(
                    f"grid {self.grid.shape} needs {self.grid.nranks} "
                    f"devices, only {len(devices)} available"
                )
            arr = np.asarray(
                devices[: self.grid.nranks], dtype=object
            ).reshape(self.grid.shape)
        return Mesh(arr.reshape(self.axis_sizes), self.axis_names)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, HierarchicalMesh)
            and self.grid == other.grid
            and self.dcn_shape == other.dcn_shape
        )

    def __hash__(self) -> int:
        return hash((HierarchicalMesh, self.grid, self.dcn_shape))

    def __repr__(self) -> str:
        return (
            f"HierarchicalMesh(grid={self.grid.shape}, "
            f"dcn={self.dcn_shape})"
        )


def stencil_offsets(ndim: int) -> Tuple[Tuple[int, ...], ...]:
    """The nonzero offsets of the 3^ndim Moore stencil, in a fixed
    (itertools.product) order — 26 in 3D. The neighbor exchange engine
    assigns one ``ppermute`` shift per offset, so the order here is the
    wire schedule's block order and must stay deterministic."""
    return tuple(
        off
        for off in itertools.product((-1, 0, 1), repeat=ndim)
        if any(off)
    )


@functools.lru_cache(maxsize=64)
def neighbor_tables(
    grid: ProcessGrid, periodic: Tuple[bool, ...]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Static Moore-stencil routing tables for ``grid``.

    Returns ``(offsets, dst, src, member)``:

    * ``offsets [n_off, ndim]`` — :func:`stencil_offsets` as an array;
    * ``dst [R, n_off] int32`` — rank that rank ``r``'s offset-``o``
      neighbor resolves to (periodic wrap per ``periodic[a]``), or ``-1``
      when the offset leaves a non-periodic grid, wraps onto ``r``
      itself, or duplicates an earlier offset's destination (extent-1/2
      axes alias neighbors; keeping only the FIRST offset per
      ``(r, dst)`` pair makes every per-offset ``ppermute`` perm
      injective);
    * ``src [R, n_off] int32`` — the rank whose offset-``o`` neighbor is
      ``r`` (i.e. the sender of block ``o`` arriving at ``r``), ``-1``
      when none — the receive-side mirror of ``dst``;
    * ``member [R, R] bool`` — ``member[r, d]`` true when ``d`` is
      reachable from ``r`` within the stencil (incl. ``d == r``); the
      out-of-stencil guard of the neighbor engine.
    """
    offs = stencil_offsets(grid.ndim)
    n_off = len(offs)
    R = grid.nranks
    dst = np.full((R, n_off), -1, dtype=np.int32)
    member = np.zeros((R, R), dtype=bool)
    for r in range(R):
        member[r, r] = True
        cell = grid.cell_of_rank(r)
        seen = set()
        for o, off in enumerate(offs):
            c = []
            ok = True
            for a in range(grid.ndim):
                x = cell[a] + off[a]
                g = grid.shape[a]
                if periodic[a]:
                    x %= g
                elif not 0 <= x < g:
                    ok = False
                    break
                c.append(x)
            if not ok:
                continue
            d = grid.rank_of_cell(tuple(c))
            if d == r or d in seen:
                continue
            seen.add(d)
            dst[r, o] = d
            member[r, d] = True
    src = np.full((R, n_off), -1, dtype=np.int32)
    for o in range(n_off):
        for r in range(R):
            d = dst[r, o]
            if d >= 0:
                src[d, o] = r
    return np.asarray(offs, dtype=np.int32), dst, src, member


def neighbor_perms(
    grid: ProcessGrid, periodic: Tuple[bool, ...]
) -> Tuple[Tuple[Tuple[int, int], ...], ...]:
    """Per-offset ``ppermute`` perm lists over the FLAT rank space (the
    mesh axes tuple, row-major — exactly ``lax.axis_index(axis_names)``):
    ``perms[o] = ((r, dst[r, o]), ...)`` over ranks with a valid
    offset-``o`` neighbor. Each perm is injective by the dedup in
    :func:`neighbor_tables`."""
    _, dst, _, _ = neighbor_tables(grid, tuple(periodic))
    return tuple(
        tuple(
            (int(r), int(dst[r, o]))
            for r in range(grid.nranks)
            if dst[r, o] >= 0
        )
        for o in range(dst.shape[1])
    )


def validate_mesh_for_grid(mesh: Mesh, grid: ProcessGrid) -> None:
    if tuple(mesh.axis_names) != tuple(grid.axis_names):
        raise ValueError(
            f"mesh axes {mesh.axis_names} != grid axes {grid.axis_names}"
        )
    mesh_shape = tuple(mesh.devices.shape)
    if mesh_shape != grid.shape:
        raise ValueError(f"mesh shape {mesh_shape} != grid shape {grid.shape}")
