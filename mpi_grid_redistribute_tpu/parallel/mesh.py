"""Device-mesh construction mirroring the Cartesian process grid.

The reference's process topology is an MPI Cartesian communicator
(SURVEY.md C1/§2 — mount empty, [DRIVER] spec); the TPU-native equivalent is
a ``jax.sharding.Mesh`` whose axes are the grid axes, so rank r of the grid
*is* device r of the mesh and XLA's ``all_to_all`` over the flattened mesh
axes reproduces the MPI rank ordering (row-major, x-major first).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from mpi_grid_redistribute_tpu.domain import ProcessGrid


def make_mesh(grid: ProcessGrid, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh shaped like ``grid`` from ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    need = grid.nranks
    if len(devices) < need:
        raise ValueError(
            f"grid {grid.shape} needs {need} devices, only "
            f"{len(devices)} available"
        )
    arr = np.asarray(devices[:need], dtype=object).reshape(grid.shape)
    return Mesh(arr, grid.axis_names)


def near_cubic_shape(n: int, ndim: int = 3) -> Tuple[int, ...]:
    """Factor ``n`` ranks into an ``ndim``-axis grid as close to cubic as
    possible (largest prime factors spread round-robin). Used when the user
    gives a device count instead of an explicit grid shape."""
    if n < 1:
        raise ValueError("need at least one rank")
    factors = []
    m = n
    d = 2
    while d * d <= m:
        while m % d == 0:
            factors.append(d)
            m //= d
        d += 1
    if m > 1:
        factors.append(m)
    shape = [1] * ndim
    for f in sorted(factors, reverse=True):
        shape[int(np.argmin(shape))] *= f
    return tuple(sorted(shape, reverse=True))


def initialize_distributed(**kwargs) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` passthrough.

    Where the reference relies on ``mpirun`` to spawn and wire R processes
    (SURVEY.md §3.1 "MPI already launched"), a multi-host TPU job runs one
    process per host and calls this once before any device use; coordinator
    address / process ids come from the TPU pod metadata automatically, or
    from the standard kwargs (coordinator_address, num_processes,
    process_id). Safe to call on a single host (no-op failure is raised by
    JAX only when misconfigured).
    """
    import jax

    jax.distributed.initialize(**kwargs)


def make_hybrid_mesh(
    grid: ProcessGrid, dcn_shape: Sequence[int] = None
) -> Mesh:
    """Mesh for multi-slice / multi-host jobs: ICI inside a slice, DCN
    across slices.

    ``dcn_shape[a]`` is how many slices the grid axis ``a`` spans (1 =
    axis stays inside a slice). Collectives along intra-slice axes ride
    ICI; only axes split across slices touch DCN — lay out the grid so the
    high-traffic axes stay intra-slice (scaling-book recipe). With
    ``dcn_shape=None`` or all-ones this reduces to :func:`make_mesh` with
    XLA's bandwidth-aware device ordering.
    """
    from jax.experimental import mesh_utils

    if dcn_shape is None:
        dcn_shape = (1,) * grid.ndim
    dcn_shape = tuple(int(d) for d in dcn_shape)
    if len(dcn_shape) != grid.ndim:
        raise ValueError(
            f"dcn_shape must have {grid.ndim} axes, got {dcn_shape}"
        )
    for a, (g, d) in enumerate(zip(grid.shape, dcn_shape)):
        if g % d:
            raise ValueError(
                f"axis {a}: grid extent {g} not divisible by dcn {d}"
            )
    if all(d == 1 for d in dcn_shape):
        devices = mesh_utils.create_device_mesh(grid.shape)
    else:
        ici = tuple(g // d for g, d in zip(grid.shape, dcn_shape))
        devices = mesh_utils.create_hybrid_device_mesh(ici, dcn_shape)
    return Mesh(devices, grid.axis_names)


def validate_mesh_for_grid(mesh: Mesh, grid: ProcessGrid) -> None:
    if tuple(mesh.axis_names) != tuple(grid.axis_names):
        raise ValueError(
            f"mesh axes {mesh.axis_names} != grid axes {grid.axis_names}"
        )
    mesh_shape = tuple(mesh.devices.shape)
    if mesh_shape != grid.shape:
        raise ValueError(f"mesh shape {mesh_shape} != grid shape {grid.shape}")
