"""Domain and process-grid specifications.

TPU-native rebuild of the reference's grid/domain spec (SURVEY.md C1, C9):
global domain bounds, Cartesian process-grid shape, rank <-> cell mapping,
and periodic-boundary flags. The reference (`dkorytov/mpi_grid_redistribute`,
mount empty at build time — see SURVEY.md §0) realizes this inside
``GridRedistribute.__init__`` over an MPI communicator; here it is a pair of
frozen dataclasses that are pure static metadata, safe to close over in
``jax.jit``/``shard_map`` traces (no device data, hashable).

Conventions:
  * The domain is an axis-aligned box ``[lo, hi)`` in ``ndim`` dimensions.
  * The process grid has the same number of axes as the domain; undecomposed
    axes use extent 1 (e.g. an 8x8 slab decomposition of a 3D box is grid
    shape ``(8, 8, 1)``).
  * Ranks are numbered row-major over grid cells (C order), matching both the
    reference's cell->rank map and ``jax.lax.axis_index`` over mesh axes
    listed x-major.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple


def _as_float_tuple(x, ndim: int, name: str) -> Tuple[float, ...]:
    if isinstance(x, (int, float)):
        return (float(x),) * ndim
    t = tuple(float(v) for v in x)
    if len(t) != ndim:
        raise ValueError(f"{name} must have length {ndim}, got {len(t)}")
    return t


@dataclasses.dataclass(frozen=True)
class Domain:
    """Axis-aligned global simulation box ``[lo, hi)``.

    Attributes:
      lo: per-axis lower bounds.
      hi: per-axis upper bounds (exclusive; a particle exactly at ``hi`` is
        wrapped when periodic, clamped into the last cell otherwise).
      periodic: per-axis periodic-boundary flags.

    Scalar ``lo``/``hi`` default to a **3D** cube; pass ``ndim=`` explicitly
    for other dimensionalities (``Domain(0.0, 1.0, ndim=2)``), or give
    per-axis sequences.
    """

    lo: Tuple[float, ...]
    hi: Tuple[float, ...]
    periodic: Tuple[bool, ...]

    def __init__(self, lo, hi, periodic=False, ndim=None):
        if ndim is None:
            if isinstance(lo, (int, float)) and isinstance(hi, (int, float)):
                ndim = 3
            else:
                ndim = len(lo) if not isinstance(lo, (int, float)) else len(hi)
        object.__setattr__(self, "lo", _as_float_tuple(lo, ndim, "lo"))
        object.__setattr__(self, "hi", _as_float_tuple(hi, ndim, "hi"))
        if isinstance(periodic, bool):
            per = (periodic,) * ndim
        else:
            per = tuple(bool(p) for p in periodic)
            if len(per) != ndim:
                raise ValueError(f"periodic must have length {ndim}")
        object.__setattr__(self, "periodic", per)
        for axis in range(ndim):
            if not self.hi[axis] > self.lo[axis]:
                raise ValueError(
                    f"domain axis {axis}: hi ({self.hi[axis]}) must exceed "
                    f"lo ({self.lo[axis]})"
                )

    @property
    def ndim(self) -> int:
        return len(self.lo)

    @property
    def extent(self) -> Tuple[float, ...]:
        return tuple(h - l for l, h in zip(self.lo, self.hi))


@dataclasses.dataclass(frozen=True)
class ProcessGrid:
    """Cartesian decomposition of the domain into one cell per rank.

    ``shape[axis]`` ranks along each axis; rank ids are row-major flat cell
    indices (cell ``(i, j, k)`` of grid ``(gx, gy, gz)`` is rank
    ``(i * gy + j) * gz + k``). ``axis_names`` are the mesh-axis names the
    JAX backend binds these grid axes to.
    """

    shape: Tuple[int, ...]
    axis_names: Tuple[str, ...]

    def __init__(self, shape: Sequence[int], axis_names: Sequence[str] = None):
        shape = tuple(int(s) for s in shape)
        if not shape or any(s < 1 for s in shape):
            raise ValueError(f"grid shape must be positive, got {shape}")
        if axis_names is None:
            default = ("x", "y", "z", "w", "v", "u")
            if len(shape) > len(default):
                raise ValueError("provide axis_names for >6D grids")
            axis_names = default[: len(shape)]
        axis_names = tuple(str(a) for a in axis_names)
        if len(axis_names) != len(shape):
            raise ValueError("axis_names must match grid shape length")
        if len(set(axis_names)) != len(axis_names):
            raise ValueError(f"axis_names must be unique, got {axis_names}")
        object.__setattr__(self, "shape", shape)
        object.__setattr__(self, "axis_names", axis_names)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def nranks(self) -> int:
        return math.prod(self.shape)

    @property
    def strides(self) -> Tuple[int, ...]:
        """Row-major strides: flat rank = sum(cell[i] * strides[i])."""
        strides = []
        acc = 1
        for s in reversed(self.shape):
            strides.append(acc)
            acc *= s
        return tuple(reversed(strides))

    def rank_of_cell(self, cell: Sequence[int]) -> int:
        if len(cell) != self.ndim:
            raise ValueError(f"cell must have {self.ndim} coordinates")
        rank = 0
        for c, s, g in zip(cell, self.strides, self.shape):
            if not 0 <= c < g:
                raise ValueError(f"cell {tuple(cell)} outside grid {self.shape}")
            rank += int(c) * s
        return rank

    def cell_of_rank(self, rank: int) -> Tuple[int, ...]:
        if not 0 <= rank < self.nranks:
            raise ValueError(f"rank {rank} outside grid of {self.nranks}")
        cell = []
        for s in self.strides:
            cell.append(rank // s)
            rank = rank % s
        return tuple(cell)

    def neighbor_rank(self, rank: int, axis: int, step: int,
                      periodic: bool) -> int:
        """Rank of the neighbor ``step`` cells along ``axis``; -1 if off-grid
        and not periodic. (The halo exchange computes neighbors implicitly
        via ``ppermute`` rings; this is for tests and custom patterns.)"""
        cell = list(self.cell_of_rank(rank))
        c = cell[axis] + step
        g = self.shape[axis]
        if periodic:
            c %= g
        elif not 0 <= c < g:
            return -1
        cell[axis] = c
        return self.rank_of_cell(cell)

    def validate_against(self, domain: Domain) -> None:
        if self.ndim != domain.ndim:
            raise ValueError(
                f"grid ndim {self.ndim} != domain ndim {domain.ndim}; pad the "
                f"grid shape with 1s for undecomposed axes, or pass "
                f"Domain(lo, hi, ndim={self.ndim}) — scalar bounds default "
                f"to a 3D domain"
            )

    def cell_widths(self, domain: Domain) -> Tuple[float, ...]:
        self.validate_against(domain)
        return tuple(e / s for e, s in zip(domain.extent, self.shape))

    def subdomain_of_rank(self, rank: int, domain: Domain):
        """(lo, hi) bounds of this rank's owned subvolume."""
        cell = self.cell_of_rank(rank)
        w = self.cell_widths(domain)
        lo = tuple(domain.lo[a] + cell[a] * w[a] for a in range(self.ndim))
        hi = tuple(domain.lo[a] + (cell[a] + 1) * w[a] for a in range(self.ndim))
        return lo, hi


@dataclasses.dataclass(frozen=True)
class GridEdges:
    """Non-uniform per-axis subdomain boundaries (SURVEY.md C1/C2's
    "np.digitize / searchsorted on edges" variant of the digitize).

    ``edges[axis]`` is a strictly increasing tuple of ``shape[axis] + 1``
    floats spanning exactly ``[domain.lo[axis], domain.hi[axis]]``; cell
    ``k`` on that axis owns ``[edges[k], edges[k+1])``. Non-uniform edges
    are the classic load-balancing complement to the LPT cell->rank
    assignment (``parallel.migrate.balanced_assignment``): instead of
    re-assigning uniform cells to ranks by measured load, the subdomain
    *boundaries themselves* move so each rank's box holds ~equal rows.

    Frozen + hashable (tuples only) so instances can parameterize the
    ``lru_cache``d exchange builders and close over ``jax.jit`` traces as
    static metadata, exactly like :class:`Domain` / :class:`ProcessGrid`.

    Scope: consumed by the canonical redistribute path (``GridRedistribute``
    / ``parallel.exchange`` / ``oracle``) via ``ops.binning``'s
    ``edges=`` parameter. The drift/migrate engines and the halo exchange
    keep uniform cells (their per-axis arithmetic is fused into Pallas
    kernels; pair non-uniform ownership with ``DriftConfig.assignment``
    there instead).

    **Assignment-aware edges** (adaptive rebalancing): with
    ``assignment`` set, the edges define a FINE cell grid —
    ``len(edges[a]) - 1`` cells per axis, typically finer than the
    process grid — and ``assignment`` maps each row-major flat fine cell
    to its owning rank. This is the LPT complement to moving boundaries:
    ``parallel.migrate.balanced_assignment`` re-bins measured per-cell
    loads onto ranks without constraining each rank's territory to a
    box, so a drifting hot spot can be split across ranks at fine-cell
    granularity. Ownership is then NON-CONTIGUOUS:
    :meth:`subdomain_of_rank` has no single box to return and raises.
    Without ``assignment`` the classic shape+1 identity mapping applies
    unchanged.
    """

    edges: Tuple[Tuple[float, ...], ...]
    assignment: Optional[Tuple[int, ...]] = None

    def __init__(
        self,
        edges: Sequence[Sequence[float]],
        assignment: Optional[Sequence[int]] = None,
    ):
        object.__setattr__(
            self,
            "edges",
            tuple(tuple(float(v) for v in ax) for ax in edges),
        )
        for a, ax in enumerate(self.edges):
            if len(ax) < 2:
                raise ValueError(
                    f"edges axis {a}: need >= 2 boundaries, got {len(ax)}"
                )
            # `not (a < b)` — NOT `a >= b` — so NaN boundaries fail too
            # (all NaN comparisons are False and would silently pass the
            # >= form, then vanish from the compare-sum digitize)
            if any(
                not (ax[i] < ax[i + 1]) for i in range(len(ax) - 1)
            ):
                raise ValueError(
                    f"edges axis {a} must be strictly increasing and "
                    f"NaN-free, got {ax}"
                )
        if assignment is not None:
            assignment = tuple(int(r) for r in assignment)
            n_cells = math.prod(self.cells_shape)
            if len(assignment) != n_cells:
                raise ValueError(
                    f"assignment has {len(assignment)} entries for "
                    f"{n_cells} cells (edges define {self.cells_shape})"
                )
            if any(r < 0 for r in assignment):
                raise ValueError("assignment ranks must be >= 0")
        object.__setattr__(self, "assignment", assignment)
        # derived (not a dataclass field — eq/hash stay on edges +
        # assignment): per-axis "is an exact np.linspace reproduction"
        # flag. Uniformly spaced axes take the floor-multiply binning
        # fast path in ops.binning instead of the per-edge digitize —
        # the rebalance planner's fine grids are always linspace-built,
        # and the compare-sum was the oracle's hot-path cost under
        # assignment-aware edges. Detection is EXACT equality with the
        # linspace reconstruction, so hand-built near-uniform edges
        # conservatively keep digitize semantics.
        import numpy as _np

        object.__setattr__(
            self,
            "uniform_axes",
            tuple(
                _np.array_equal(
                    _np.asarray(ax, dtype=_np.float64),
                    _np.linspace(ax[0], ax[-1], len(ax)),
                )
                for ax in self.edges
            ),
        )

    @property
    def ndim(self) -> int:
        return len(self.edges)

    @property
    def cells_shape(self) -> Tuple[int, ...]:
        """Per-axis cell counts these edges define (``len(edges[a]) - 1``).
        Equals ``grid.shape`` for identity-mapped edges; finer for
        assignment-aware edges."""
        return tuple(len(ax) - 1 for ax in self.edges)

    @property
    def cell_strides(self) -> Tuple[int, ...]:
        """Row-major strides over :attr:`cells_shape` (flat fine-cell id =
        ``sum(cell[a] * cell_strides[a])`` — the index into
        :attr:`assignment`)."""
        strides = []
        acc = 1
        for s in reversed(self.cells_shape):
            strides.append(acc)
            acc *= s
        return tuple(reversed(strides))

    def validate_against(self, domain: Domain, grid: ProcessGrid) -> None:
        grid.validate_against(domain)
        if self.ndim != grid.ndim:
            raise ValueError(
                f"edges ndim {self.ndim} != grid ndim {grid.ndim}"
            )
        for a, ax in enumerate(self.edges):
            if self.assignment is None and len(ax) != grid.shape[a] + 1:
                raise ValueError(
                    f"edges axis {a}: {len(ax)} boundaries for "
                    f"{grid.shape[a]} cells (need shape+1, or pass an "
                    f"assignment for finer-than-grid cells)"
                )
            if ax[0] != domain.lo[a] or ax[-1] != domain.hi[a]:
                raise ValueError(
                    f"edges axis {a} must span [{domain.lo[a]}, "
                    f"{domain.hi[a]}] exactly, got [{ax[0]}, {ax[-1]}]"
                )
        if self.assignment is not None and max(self.assignment) >= grid.nranks:
            raise ValueError(
                f"assignment references rank {max(self.assignment)} but "
                f"grid {grid.shape} has only {grid.nranks} ranks"
            )

    def subdomain_of_rank(self, rank: int, grid: ProcessGrid):
        """(lo, hi) bounds of ``rank``'s owned subvolume under these edges.

        Only defined for identity-mapped edges: an ``assignment`` makes a
        rank's territory a union of fine cells, not a box."""
        if self.assignment is not None:
            raise ValueError(
                "subdomain_of_rank is undefined for assignment-aware "
                "edges: a rank owns a set of fine cells, not one box — "
                "enumerate cells via rank_cells_of instead"
            )
        cell = grid.cell_of_rank(rank)
        lo = tuple(self.edges[a][cell[a]] for a in range(self.ndim))
        hi = tuple(self.edges[a][cell[a] + 1] for a in range(self.ndim))
        return lo, hi

    def rank_cells_of(self, rank: int) -> Tuple[int, ...]:
        """Flat fine-cell ids owned by ``rank`` under :attr:`assignment`
        (empty tuple when the rank owns no cells — legal under LPT when
        there are more ranks than loaded cells)."""
        if self.assignment is None:
            raise ValueError(
                "rank_cells_of needs assignment-aware edges; identity "
                "edges map grid cell == rank (use grid.cell_of_rank)"
            )
        return tuple(
            c for c, r in enumerate(self.assignment) if r == rank
        )

    @staticmethod
    def balanced_for(
        domain: Domain, grid: ProcessGrid, positions
    ) -> "GridEdges":
        """Edges placing ~equal row counts per slab along each axis
        (independent per-axis quantiles of the supplied sample positions —
        the standard recursive-bisection-style balance for product grids).

        ``positions`` is a host array ``[N, ndim]``; samples are
        periodic-wrapped into the domain first (drifted inputs are legal
        ``redistribute`` arguments — the wrap happens inside the engine
        too), and quantile edges are snapped to the domain bounds at the
        ends.
        """
        import numpy as _np

        grid.validate_against(domain)
        shp = _np.shape(positions)
        if len(shp) != 2 or shp[1] != grid.ndim:
            raise ValueError(
                f"positions must be [N, {grid.ndim}], got {shp}"
            )
        # one copy total (np.array always copies; asarray+copy would
        # double the host transient at large samples)
        pos = _np.array(positions, dtype=_np.float64)
        for a in range(grid.ndim):
            lo, ext = domain.lo[a], domain.extent[a]
            if domain.periodic[a]:
                pos[:, a] = lo + _np.remainder(pos[:, a] - lo, ext)
            else:
                # mirror the engine's clamp-into-edge-cells semantics so
                # out-of-box samples on non-periodic axes cannot push
                # quantiles outside [lo, hi]
                pos[:, a] = _np.clip(pos[:, a], lo, lo + ext)
        axes_edges = []
        for a in range(grid.ndim):
            g = grid.shape[a]
            qs = _np.quantile(pos[:, a], _np.linspace(0.0, 1.0, g + 1))
            qs[0], qs[-1] = domain.lo[a], domain.hi[a]
            # Enforce strict monotonicity on degenerate samples: push
            # colliding quantiles up from lo, then pull any that landed
            # on hi back down (a point mass AT hi — e.g. a fully-clamped
            # non-periodic axis — makes the upper quantiles equal hi).
            # Point-mass samples thus yield VALID edges whose empty-ish
            # slabs merely reflect that balance is impossible, the same
            # best-effort behavior mid-domain atoms already got.
            for i in range(1, g + 1):
                if qs[i] <= qs[i - 1]:
                    qs[i] = _np.nextafter(qs[i - 1], _np.inf)
            qs[-1] = domain.hi[a]
            for i in range(g - 1, 0, -1):
                if qs[i] >= qs[i + 1]:
                    qs[i] = _np.nextafter(qs[i + 1], -_np.inf)
            if any(qs[i] <= qs[i - 1] for i in range(1, g + 1)):
                # float spacing exhausted between lo and hi — only
                # possible for absurd g or a zero-extent-scale domain
                raise ValueError(
                    f"axis {a}: cannot place {g} non-empty slabs in "
                    f"[{domain.lo[a]}, {domain.hi[a]}]"
                )
            axes_edges.append(tuple(float(v) for v in qs))
        return GridEdges(axes_edges)
