"""Telemetry query plane: filter / window / group over any journal.

One API over every event surface the stack produces — a live
:class:`~.recorder.StepRecorder`, a :class:`~.aggregate.MergedJournal`
pod view, a :class:`~.store.StoreReader` over durable segments, a JSONL
shard path, or any iterable of decoded rows. ``rows_of`` normalises
them all to envelope-ordered dict rows; the layers compose:

    rows   = rows_of(source)
    rows   = filter_rows(rows, kind="step_latency", step_min=1000)
    series = window_aggregate(rows, op="p99", window_s=5.0)
    groups = group_rows(rows, by="trace")

``run_query`` is the HTTP-facing entry: it takes the flat string
parameter dict ``GET /query`` parses (see the grammar in
telemetry/SCHEMA.md) and returns a JSON-able result. ``events_page``
backs the cursor-resumable ``GET /events`` stream — the cursor is the
``host:pid:seq`` envelope triple, the exact total order
``aggregate.merge_journals`` sorts by, so a client that reconnects
resumes without loss or duplication.

Compacted stores stay first-class: ``store_window`` summary rows carry
histogram sketches on ``metrics.STEP_TIME_EDGES``, and the quantile ops
merge those sketches with raw ``step_latency`` samples in the same
:class:`~.metrics.Histogram`, so a p99 over a half-compacted range is
the p99 — not an approximation of one.

Scrape-path purity: stdlib + the jax-free telemetry siblings only
(G007; loaded with jax absent by ``tests/test_metrics.py``).
"""

from __future__ import annotations

# gridlint: scrape-path

import json
import math
from typing import Dict, Iterable, List, Optional, Tuple

from . import metrics as metrics_lib

#: Envelope keys every normalised row carries (when the source had
#: them); everything else is event payload.
ENVELOPE = ("seq", "time", "kind", "host", "pid", "t_aligned")

#: ``op=`` values ``window_aggregate`` understands.
AGG_OPS = (
    "count",
    "rate",
    "sum",
    "mean",
    "min",
    "max",
    "p50",
    "p90",
    "p99",
    "ema",
)

#: ``by=`` values ``group_rows`` understands.
GROUP_KEYS = ("kind", "trace", "host", "pid", "vrank")


class QueryError(ValueError):
    """Malformed query parameters (bad op, bad number, unknown key).
    Maps to HTTP 400 on the ``/query`` endpoint."""


# --------------------------------------------------------------- rows


def _row_time(row: dict) -> float:
    t = row.get("t_aligned", row.get("time"))
    return float(t) if t is not None else 0.0


def _row_order(row: dict) -> Tuple[float, str, int, int]:
    # the merge_journals total order: aligned wall, then shard identity,
    # then the shard-local monotone seq
    return (
        _row_time(row),
        str(row.get("host", "")),
        int(row.get("pid", 0)),
        int(row.get("seq", 0)),
    )


def rows_of(source) -> List[dict]:
    """Normalise any journal source to a sorted list of decoded rows.

    Accepts a ``StepRecorder`` (events get the recorder's host/pid
    tags), a ``MergedJournal``, a ``StoreReader``, a JSONL path or open
    file, or an iterable of already-decoded dicts. Rows come back in
    ``(time, host, pid, seq)`` envelope order."""
    rows: List[dict]
    if hasattr(source, "events") and hasattr(source, "counts"):
        raw = source.events()
        rows = []
        tags = {}
        if hasattr(source, "host") and hasattr(source, "pid"):
            tags = {"host": source.host, "pid": source.pid}
        for e in raw:
            if isinstance(e, dict):
                rows.append(dict(e))
            else:  # recorder Event namedtuples
                rows.append(json.loads(e.to_json(tags)))
    elif isinstance(source, (str, bytes)) or hasattr(source, "read"):
        f = open(source, encoding="utf-8") if isinstance(
            source, (str, bytes)
        ) else source
        try:
            rows = [
                json.loads(ln)
                for ln in f
                if ln.strip()
            ]
        finally:
            if f is not source:
                f.close()
    else:
        rows = [dict(r) for r in source]
    rows.sort(key=_row_order)
    return rows


# ------------------------------------------------------------ filters


def _step_of(row: dict) -> Optional[int]:
    s = row.get("step", row.get("ctx_step"))
    if s is None and row.get("kind") == "store_window":
        s = row.get("step_min")
    return int(s) if s is not None else None


def filter_rows(
    rows: Iterable[dict],
    kind: Optional[str] = None,
    step_min: Optional[int] = None,
    step_max: Optional[int] = None,
    trace: Optional[str] = None,
    host: Optional[str] = None,
    pid: Optional[int] = None,
    since: Optional[float] = None,
    until: Optional[float] = None,
    ctx: Optional[Dict[str, str]] = None,
) -> List[dict]:
    """Filter by envelope and context fields. ``kind`` accepts a
    comma-separated set. Step bounds match the event's ``step`` payload
    or its ``ctx_step`` envelope (and a ``store_window``'s step span);
    rows with neither pass only when no step bound is set. ``ctx``
    matches arbitrary ``ctx_*`` fields by string equality."""
    kinds = set(kind.split(",")) if kind else None
    out = []
    for r in rows:
        if kinds is not None and r.get("kind") not in kinds:
            continue
        if host is not None and str(r.get("host")) != str(host):
            continue
        if pid is not None and int(r.get("pid", -1)) != int(pid):
            continue
        if trace is not None and str(r.get("ctx_trace")) != str(trace):
            continue
        if step_min is not None or step_max is not None:
            s = _step_of(r)
            s_hi = r.get("step_max", s) if r.get("kind") == "store_window" else s
            if s is None:
                continue
            if step_min is not None and (
                s_hi if s_hi is not None else s
            ) < step_min:
                continue
            if step_max is not None and s > step_max:
                continue
        t = _row_time(r)
        if since is not None and t < since:
            continue
        if until is not None and t > until:
            continue
        if ctx:
            ok = all(
                str(r.get(f"ctx_{k}", r.get(k))) == str(v)
                for k, v in ctx.items()
            )
            if not ok:
                continue
        out.append(r)
    return out


# ----------------------------------------------------------- group-by


def group_rows(rows: Iterable[dict], by: str) -> Dict[str, List[dict]]:
    """Partition rows by ``kind``/``trace``/``host``/``pid``/``vrank``.

    ``vrank`` explodes per-rank vector payloads (``sent_per_rank`` etc.
    on ``migrate_step`` rows) into one synthetic row per rank carrying
    ``vrank`` and the scalar slice — the per-rank drill-down the flow
    plane's imbalance attribution wants."""
    if by not in GROUP_KEYS:
        raise QueryError(f"unknown group key {by!r}; one of {GROUP_KEYS}")
    out: Dict[str, List[dict]] = {}
    if by == "vrank":
        for r in rows:
            vectors = {
                k: v
                for k, v in r.items()
                if k.endswith("_per_rank") and isinstance(v, (list, tuple))
            }
            if not vectors:
                continue
            n = max(len(v) for v in vectors.values())
            for rank in range(n):
                slice_row = {
                    k: v for k, v in r.items() if k not in vectors
                }
                slice_row["vrank"] = rank
                for k, v in vectors.items():
                    if rank < len(v):
                        slice_row[k[: -len("_per_rank")]] = v[rank]
                out.setdefault(str(rank), []).append(slice_row)
        return out
    key = {"trace": "ctx_trace"}.get(by, by)
    for r in rows:
        out.setdefault(str(r.get(key)), []).append(r)
    return out


# -------------------------------------------------------- aggregation


def _window_values(row: dict, field: str) -> List[float]:
    """Scalar samples a row contributes to a windowed aggregate over
    ``field``. ``store_window`` rows contribute their per-window
    totals/means for count-like fields (exactness preserved)."""
    if row.get("kind") == "store_window":
        if field == "seconds":
            return []  # quantile ops merge the sketch instead
        if field == "dropped":
            return [float(row.get("dropped", {}).get("total", 0))]
        v = row.get(field)
        return [float(v)] if isinstance(v, (int, float)) else []
    v = row.get(field)
    return [float(v)] if isinstance(v, (int, float)) else []


def _row_weight(row: dict) -> int:
    """How many source events a row stands for (summary rows compress
    many) — what ``count``/``rate`` windows sum."""
    if row.get("kind") == "store_window":
        return int(row.get("events", 1))
    return 1


def window_aggregate(
    rows: Iterable[dict],
    op: str = "count",
    field: str = "seconds",
    window_s: float = 10.0,
    ema_alpha: float = 0.3,
) -> List[dict]:
    """Bucket rows into fixed wall-clock windows and reduce each.

    Returns ``[{"t": window_start, "n": events, "value": reduced}]``
    sorted by time. Quantile ops (``p50``/``p90``/``p99``) build a
    ``metrics.Histogram`` on ``STEP_TIME_EDGES`` per window, merging
    ``store_window`` latency sketches with raw samples — the same
    bucketed upper-bound estimate ``/metrics`` readers compute. ``ema``
    smooths per-window means with ``ema_alpha``. ``rate`` is events per
    second (summary rows weighted by the events they compress)."""
    if op not in AGG_OPS:
        raise QueryError(f"unknown op {op!r}; one of {AGG_OPS}")
    if window_s <= 0:
        raise QueryError(f"window_s must be > 0, got {window_s}")
    rows = sorted(rows, key=_row_order)
    if not rows:
        return []
    t0 = _row_time(rows[0])
    buckets: Dict[int, List[dict]] = {}
    for r in rows:
        buckets.setdefault(int((_row_time(r) - t0) // window_s), []).append(r)
    out = []
    prev_ema: Optional[float] = None
    for i in sorted(buckets):
        group = buckets[i]
        n = sum(_row_weight(r) for r in group)
        value: Optional[float]
        if op in ("p50", "p90", "p99"):
            # exact-bucket quantile: raw samples observed, compacted
            # sketches merged — identical edges, identical answer
            from . import store as store_lib

            h = metrics_lib.Histogram((), metrics_lib.STEP_TIME_EDGES)
            sketches = []
            for r in group:
                if r.get("kind") == "store_window":
                    key = "step_time" if field == "step_time" else "latency"
                    sketches.append(r.get(key))
                else:
                    for v in _window_values(r, field):
                        h.observe(v)
            merged = store_lib.sketch_to_histogram(sketches)
            for j, cnt in enumerate(merged._bucket_counts):
                h._bucket_counts[j] += cnt
            h._sum += merged._sum
            h._count += merged._count
            q = {"p50": 0.5, "p90": 0.9, "p99": 0.99}[op]
            value = h.quantile(q) if h.count else None
            if value is not None and math.isinf(value):
                value = None
            n = h.count if h.count else n
        else:
            vals: List[float] = []
            for r in group:
                vals.extend(_window_values(r, field))
            if op == "count":
                value = float(n)
            elif op == "rate":
                value = n / window_s
            elif op == "sum":
                value = sum(vals) if vals else 0.0
            elif op == "mean":
                value = sum(vals) / len(vals) if vals else None
            elif op == "min":
                value = min(vals) if vals else None
            elif op == "max":
                value = max(vals) if vals else None
            else:  # ema
                mean = sum(vals) / len(vals) if vals else None
                if mean is None:
                    value = prev_ema
                elif prev_ema is None:
                    value = prev_ema = mean
                else:
                    value = prev_ema = (
                        ema_alpha * mean + (1.0 - ema_alpha) * prev_ema
                    )
        out.append({"t": t0 + i * window_s, "n": n, "value": value})
    return out


# ------------------------------------------------------------ cursors


def cursor_of(row: dict) -> str:
    """Opaque-but-stable resume token: the ``host:pid:seq`` envelope
    triple — the same total order the pod merge sorts by."""
    return f"{row.get('host', '')}:{row.get('pid', 0)}:{row.get('seq', 0)}"


def parse_cursor(cursor: str) -> Tuple[str, int, int]:
    try:
        host, pid, seq = cursor.rsplit(":", 2)
        return host, int(pid), int(seq)
    except ValueError as e:
        raise QueryError(f"bad cursor {cursor!r}: {e}") from e


def after_cursor(rows: List[dict], cursor: Optional[str]) -> List[dict]:
    """Rows strictly after ``cursor`` in envelope order. An exact match
    resumes positionally; a cursor whose exact row has been evicted or
    compacted resumes at the first row of the same ``host:pid`` shard
    with a larger ``seq`` (no duplicates, bounded loss — the shard's
    monotone seq makes this safe); an unknown shard replays all rows."""
    if not cursor:
        return list(rows)
    host, pid, seq = parse_cursor(cursor)
    for i, r in enumerate(rows):
        if (
            str(r.get("host", "")) == host
            and int(r.get("pid", 0)) == pid
            and int(r.get("seq", 0)) == seq
        ):
            return rows[i + 1:]
    # exact row gone: positional fallback within the shard's seq order
    for i, r in enumerate(rows):
        if (
            str(r.get("host", "")) == host
            and int(r.get("pid", 0)) == pid
            and int(r.get("seq", 0)) > seq
        ):
            return rows[i:]
    known = any(
        str(r.get("host", "")) == host and int(r.get("pid", 0)) == pid
        for r in rows
    )
    return [] if known else list(rows)


def events_page(
    rows: List[dict],
    cursor: Optional[str] = None,
    limit: int = 256,
) -> dict:
    """One ``GET /events`` page: up to ``limit`` rows after ``cursor``
    plus the cursor to resume from. ``cursor`` in the reply always
    advances (it echoes the input when no rows are ready), so a client
    can long-poll in a loop with no state beyond the last reply."""
    if limit < 1:
        raise QueryError(f"limit must be >= 1, got {limit}")
    pending = after_cursor(rows, cursor)
    page = pending[:limit]
    next_cursor = cursor_of(page[-1]) if page else (cursor or "")
    return {
        "events": page,
        "cursor": next_cursor,
        "remaining": len(pending) - len(page),
    }


# ------------------------------------------------------- HTTP grammar

_INT_PARAMS = ("step_min", "step_max", "pid", "limit")
_FLOAT_PARAMS = ("since", "until", "window_s", "ema_alpha")


def run_query(source, params: Dict[str, str]) -> dict:
    """Execute the flat-string parameter grammar ``GET /query`` parses
    (telemetry/SCHEMA.md "Query parameter grammar") and return a
    JSON-able reply.

    Filters: ``kind``, ``step_min``/``step_max``, ``trace``, ``host``,
    ``pid``, ``since``/``until``, ``ctx.<field>=<value>``. Shapes:
    ``agg=<op>`` (+ ``field``, ``window_s``, ``ema_alpha``) for a
    windowed series, ``by=<key>`` for grouped counts, neither for the
    matching rows (capped by ``limit``, newest kept)."""
    params = dict(params)
    ctx = {
        k[len("ctx."):]: params.pop(k)
        for k in list(params)
        if k.startswith("ctx.")
    }
    parsed: Dict[str, object] = {}
    for k, v in params.items():
        if k in _INT_PARAMS:
            try:
                parsed[k] = int(v)
            except ValueError as e:
                raise QueryError(f"bad integer for {k}: {v!r}") from e
        elif k in _FLOAT_PARAMS:
            try:
                parsed[k] = float(v)
            except ValueError as e:
                raise QueryError(f"bad number for {k}: {v!r}") from e
        elif k in ("kind", "trace", "host", "agg", "by", "field", "cursor"):
            parsed[k] = v
        else:
            raise QueryError(f"unknown query parameter {k!r}")
    rows = filter_rows(
        rows_of(source),
        kind=parsed.get("kind"),
        step_min=parsed.get("step_min"),
        step_max=parsed.get("step_max"),
        trace=parsed.get("trace"),
        host=parsed.get("host"),
        pid=parsed.get("pid"),
        since=parsed.get("since"),
        until=parsed.get("until"),
        ctx=ctx or None,
    )
    reply: Dict[str, object] = {"matched": len(rows)}
    if "agg" in parsed:
        reply["series"] = window_aggregate(
            rows,
            op=str(parsed["agg"]),
            field=str(parsed.get("field", "seconds")),
            window_s=float(parsed.get("window_s", 10.0)),
            ema_alpha=float(parsed.get("ema_alpha", 0.3)),
        )
    elif "by" in parsed:
        groups = group_rows(rows, by=str(parsed["by"]))
        reply["groups"] = {k: len(v) for k, v in sorted(groups.items())}
    else:
        limit = int(parsed.get("limit", 256))
        if limit < 1:
            raise QueryError(f"limit must be >= 1, got {limit}")
        reply["events"] = rows[-limit:]
    return reply
