"""Always-on health monitor: declarative rules over the telemetry journal.

Production systems page on *signals*, not on someone re-deriving a stall
from raw counters. :class:`HealthMonitor` closes the loop between the
journal (:class:`~.recorder.StepRecorder` events, including
``flow_snapshot`` gauges from :mod:`.flow`) and the operator: a small
set of declarative rules is evaluated on demand (``rd.health()``, bench
boundaries, ``make observe``); each finding fires the registered
callbacks AND records an ``alert`` event into the same ring, so alerts
appear in the JSONL export and the Perfetto timeline next to the events
that caused them.

Evaluation is host-side dict scans only — the monitor never touches the
device, so it keeps the recorder's steady-state contract (overhead gated
at <= 2% of the config1 CPU step time, ``tests/test_flow.py``).

The stock rules (:func:`default_rules`):

* ``backlog_growth`` — total backlog strictly monotone increasing over
  the last ``window`` ``migrate_step`` events (the drift-workload
  failure mode: one shard fills and sends stop draining). ALERT.
* ``dropped_rows`` — any ``migrate_step`` event with ``dropped_recv >
  0``, or any ``overflow_window_loss`` ever (all-time counts, so a loss
  that scrolled off the ring still fires). ALERT.
* ``capacity_grow_frequency`` — more than ``max_grows`` capacity/halo
  grows within the retained window: capacities are thrashing instead of
  converging to the workload. WARN.
* ``imbalance_ratio`` — the latest ``flow_snapshot``'s max/mean
  population gauge above ``threshold``. WARN.
* ``step_time_spike`` — the latest ``step_time`` event above ``factor``
  x the EMA of the preceding ones (feed :meth:`HealthMonitor.note_step_time`
  from the driver's timing loop). WARN.
* ``fast_path_fallback`` — the sparse migrate engine fell back to the
  dense planar path on more than ``threshold`` of the last ``window``
  ``fast_path`` events: ``mover_cap`` is undersized (or the workload is
  not mover-sparse) and every step pays guard + dense cost. WARN.
* ``snapshot_staleness`` — wall time since the last ``snapshot`` event
  exceeds ``factor`` x its recorded cadence: the service driver's
  checkpoint writer has stalled or died, so a crash now loses more work
  than the restart policy budgets for. WARN.
* ``nan_detected`` — any retained ``state_health`` event with a
  nonzero NaN/Inf row count (armed probes only, ISSUE 20); the reason
  names the corrupting step. ALERT.
* ``conservation_drift`` — any retained ``state_health`` event with a
  nonzero exact conservation residual (rows appeared or vanished
  unaccounted). ALERT.
* ``bounds_violation`` — any retained ``state_health`` event with live
  rows outside the probe's domain box. ALERT.

This list IS the contract: SCHEMA.md's "Health rule table" mirrors it
name-for-name in the same order with the same severities, and the drift
test in ``tests/test_probes.py`` fails the suite when they disagree.

Opt-in SLO rules (installed by the service driver when its SLO knobs
are set; they actuate the restart/shrink policy, ISSUE 8):

* ``slo_latency_p99`` — bucketed p99 of the last ``window``
  ``step_latency`` events above the latency SLO. ALERT.
* ``slo_dropped_rows`` — bucketed p99 of per-step dropped rows above
  the loss SLO (default 0: any sustained loss). ALERT.
* ``burn_rate_latency`` / ``burn_rate_dropped`` — multi-window
  error-budget burn rates over the same pow2 histograms: the fraction of
  recent steps violating the SLO, divided by the budget the objective
  leaves (1 - objective), checked over a short *fast* window (pages on
  sudden total breach within minutes of evidence) and a long *slow*
  window (catches sustained low-grade burn the fast window forgives).
  The SRE-standard upgrade of the point-in-time p99 rules; the reason
  string names the window and burn factor that fired. ALERT.

Callbacks registered on the monitor (``add_callback`` /
``on_alert=``) are isolated: a callback that raises is journaled as a
``callback_error`` event and evaluation continues with the remaining
rules — a broken alert sink can never mask a real ALERT.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from mpi_grid_redistribute_tpu.telemetry.recorder import StepRecorder

OK = "OK"
WARN = "WARN"
ALERT = "ALERT"
_SEVERITY_ORDER = {OK: 0, WARN: 1, ALERT: 2}

# Event kinds the observability plane itself emits while reacting to
# findings. Excluded from the alert-dedup clock in
# :meth:`HealthMonitor.evaluate` so reacting to an alert is never "new
# evidence" that re-fires the same alert.
_META_KINDS = ("alert", "callback_error", "incident")


class HealthRule(NamedTuple):
    """One declarative rule: ``fn(recorder)`` returns a human reason
    string when the rule fires, ``None`` when healthy. ``severity`` is
    :data:`WARN` or :data:`ALERT`."""

    name: str
    severity: str
    fn: Callable[[StepRecorder], Optional[str]]


class Finding(NamedTuple):
    """One fired rule from a :meth:`HealthMonitor.evaluate` pass."""

    rule: str
    severity: str
    reason: str


def backlog_growth(window: int = 4) -> HealthRule:
    """ALERT when total backlog grows strictly monotonically over the
    last ``window`` ``migrate_step`` events (and ends nonzero)."""
    if window < 2:
        raise ValueError(f"window must be >= 2, got {window}")

    def fn(rec: StepRecorder) -> Optional[str]:
        ev = rec.events("migrate_step")[-window:]
        if len(ev) < window:
            return None
        backlog = [int(e.data.get("backlog", 0)) for e in ev]
        growing = all(b > a for a, b in zip(backlog, backlog[1:]))
        if growing and backlog[-1] > 0:
            return (
                f"backlog grew monotonically over the last {window} "
                f"steps: {backlog[0]} -> {backlog[-1]}"
            )
        return None

    return HealthRule("backlog_growth", ALERT, fn)


def dropped_rows() -> HealthRule:
    """ALERT on any surfaced row loss: a ``migrate_step`` event with
    ``dropped_recv > 0``, or any all-time ``overflow_window_loss``."""

    def fn(rec: StepRecorder) -> Optional[str]:
        losses = rec.counts().get("overflow_window_loss", 0)
        if losses:
            return f"{losses} overflow window(s) resolved with loss"
        for e in rec.events("migrate_step"):
            d = int(e.data.get("dropped_recv", 0))
            if d > 0:
                return f"dropped_recv={d} at step {e.data.get('step')}"
        return None

    return HealthRule("dropped_rows", ALERT, fn)


def capacity_grow_frequency(max_grows: int = 3) -> HealthRule:
    """WARN when more than ``max_grows`` capacity/halo grow events are
    retained in the ring — capacities are thrashing, not converging."""

    def fn(rec: StepRecorder) -> Optional[str]:
        grows = len(rec.events("capacity_grow")) + len(
            rec.events("halo_grow")
        )
        if grows > max_grows:
            return (
                f"{grows} capacity grows in the retained window "
                f"(> {max_grows}): workload outruns the size estimate"
            )
        return None

    return HealthRule("capacity_grow_frequency", WARN, fn)


def imbalance_ratio(
    threshold: float = 2.0, severity: str = WARN
) -> HealthRule:
    """Fire when the latest ``flow_snapshot`` population imbalance
    (max/mean) exceeds ``threshold``. WARN by default (advisory for an
    operator); the service driver's adaptive-rebalance loop installs an
    ALERT-severity copy at its actuation threshold, since for it the
    finding is a trigger, not a notice."""
    if severity not in (WARN, ALERT):
        raise ValueError(f"severity must be WARN or ALERT, got {severity!r}")

    def fn(rec: StepRecorder) -> Optional[str]:
        e = rec.last("flow_snapshot")
        if e is None:
            return None
        imb = float(e.data.get("imbalance", 0.0))
        if imb > threshold:
            return (
                f"population imbalance {imb:.2f}x (max/mean) exceeds "
                f"{threshold:.2f}x"
            )
        return None

    return HealthRule("imbalance_ratio", severity, fn)


def step_time_spike(factor: float = 3.0, min_samples: int = 4) -> HealthRule:
    """WARN when the newest ``step_time`` event exceeds ``factor`` x the
    EMA of the preceding ones (recompile, contention, thermal event)."""

    def fn(rec: StepRecorder) -> Optional[str]:
        ev = rec.events("step_time")
        if len(ev) < min_samples:
            return None
        times = [float(e.data.get("seconds", 0.0)) for e in ev]
        ema = times[0]
        for t in times[1:-1]:
            ema = 0.2 * t + 0.8 * ema
        if ema > 0 and times[-1] > factor * ema:
            return (
                f"step time {times[-1] * 1e3:.2f} ms is "
                f"{times[-1] / ema:.1f}x the {ema * 1e3:.2f} ms EMA"
            )
        return None

    return HealthRule("step_time_spike", WARN, fn)


def fast_path_fallback(
    window: int = 16, threshold: float = 0.5
) -> HealthRule:
    """WARN when more than ``threshold`` of the last ``window``
    ``fast_path`` events took the dense fallback — the sparse engine is
    compiled in but mostly not running (undersized ``mover_cap`` or a
    workload that is not mover-sparse), so steps pay the routing guard
    on top of the full dense cost. Needs a full window of events before
    it can fire (a cold journal is not evidence)."""
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")

    def fn(rec: StepRecorder) -> Optional[str]:
        ev = rec.events("fast_path")[-window:]
        if len(ev) < window:
            return None
        fallbacks = sum(1 - int(e.data.get("taken", 0)) for e in ev)
        rate = fallbacks / len(ev)
        if rate > threshold:
            return (
                f"sparse fast path fell back on {fallbacks}/{len(ev)} of "
                f"the last steps ({rate:.0%} > {threshold:.0%}): grow "
                f"mover_cap or run engine='planar'"
            )
        return None

    return HealthRule("fast_path_fallback", WARN, fn)


def snapshot_staleness(factor: float = 2.0) -> HealthRule:
    """WARN when the wall time since the last ``snapshot`` event exceeds
    ``factor`` x the cadence that event recorded (``cadence_s``, the
    service driver's ``snapshot_every`` x step-time EMA). A stale
    snapshot means the checkpoint writer is stalled or dead: the state
    at risk on a crash keeps growing past what the restart policy
    budgets for. Quiet until a snapshot with a known cadence exists —
    a run with snapshots off is not evidence of staleness."""
    if factor <= 0:
        raise ValueError(f"factor must be > 0, got {factor}")

    def fn(rec: StepRecorder) -> Optional[str]:
        e = rec.last("snapshot")
        if e is None:
            return None
        cadence = float(e.data.get("cadence_s", 0.0))
        if cadence <= 0.0:
            return None  # cadence unknown (cold step-time EMA)
        age = time.time() - e.time
        if age > factor * cadence:
            return (
                f"last snapshot (step {e.data.get('step')}) is "
                f"{age:.1f}s old, > {factor:.1f}x the {cadence:.1f}s "
                f"cadence: checkpoint writer stalled or dead"
            )
        return None

    return HealthRule("snapshot_staleness", WARN, fn)


def _fresh_state_events(rec: StepRecorder):
    """``state_health`` events journaled AFTER the newest supervised
    state restore. A restore rolls the particle state back to a
    pre-corruption snapshot, so corruption evidence older than it
    describes state that no longer exists — without this cut a
    recovered service would page on its own history until the ring
    scrolled, and the supervisor's post-run ``healthz`` poll would turn
    one rolled-back NaN burst into a permanent crash loop."""
    ev = rec.events("state_health")
    if not ev:
        return ev
    restores = [
        e for e in rec.events("restore") if e.data.get("what") == "state"
    ]
    if not restores:
        return ev
    cut = restores[-1].seq
    return [e for e in ev if e.seq > cut]


def nan_detected() -> HealthRule:
    """ALERT on the first fresh ``state_health`` event whose NaN/Inf
    row count is nonzero (``nan_pos + nan_vel > 0``) — non-finite
    particle state is corruption the moment it exists, never load. The
    reason names the step, so the incident bundle's index pins exactly
    where the corruption entered. Quiet when probes are off (no
    ``state_health`` events is not evidence), and quiet about
    corruption an intervening state restore already rolled back
    (:func:`_fresh_state_events`)."""

    def fn(rec: StepRecorder) -> Optional[str]:
        for e in _fresh_state_events(rec):
            n_pos = int(e.data.get("nan_pos", 0))
            n_vel = int(e.data.get("nan_vel", 0))
            if n_pos or n_vel:
                return (
                    f"non-finite state at step {e.data.get('step')}: "
                    f"nan_pos={n_pos} nan_vel={n_vel} live rows corrupt"
                )
        return None

    return HealthRule("nan_detected", ALERT, fn)


def conservation_drift() -> HealthRule:
    """ALERT on the first retained ``state_health`` event whose exact
    int32 conservation residual (``live + dropped - initial``) is
    nonzero — rows appeared or vanished without being accounted by the
    exchange's own drop counters. Exact by construction: any nonzero
    value fires, there is no threshold to tune. Like the other state
    rules, only evidence newer than the latest state restore counts
    (:func:`_fresh_state_events`)."""

    def fn(rec: StepRecorder) -> Optional[str]:
        for e in _fresh_state_events(rec):
            r = int(e.data.get("residual", 0))
            if r != 0:
                return (
                    f"conservation residual {r:+d} rows at step "
                    f"{e.data.get('step')}: live + dropped != initial"
                )
        return None

    return HealthRule("conservation_drift", ALERT, fn)


def bounds_violation() -> HealthRule:
    """ALERT on the first retained ``state_health`` event with live
    rows outside the probe's domain box (``oob > 0``). The periodic
    drift wraps every position into [0, 1), so an out-of-bounds row
    means a broken integrator or wrap, not a fast particle. NaN rows
    are counted by ``nan_detected`` only (IEEE comparisons are false
    both ways), so the two rules partition the corrupt rows. Only
    evidence newer than the latest state restore counts
    (:func:`_fresh_state_events`)."""

    def fn(rec: StepRecorder) -> Optional[str]:
        for e in _fresh_state_events(rec):
            oob = int(e.data.get("oob", 0))
            if oob:
                return (
                    f"{oob} live rows out of the domain box at step "
                    f"{e.data.get('step')}"
                )
        return None

    return HealthRule("bounds_violation", ALERT, fn)


def slo_latency_p99(
    threshold_s: float, window: int = 16, q: float = 0.99
) -> HealthRule:
    """ALERT when the bucketed ``q``-quantile of the last ``window``
    ``step_latency`` events exceeds ``threshold_s``.

    The quantile is computed through the same pow2-bucket histogram the
    metrics plane scrapes (``grid_step_latency_seconds``), so the value
    that trips the restart policy is the value an operator sees on
    ``/metrics`` — not a slightly different exact-percentile. Needs a
    full window before it can fire (a cold journal is not a breach), so
    a post-restart driver gets ``window`` healthy steps to prove itself
    before old spikes scroll out."""
    if threshold_s <= 0:
        raise ValueError(f"threshold_s must be > 0, got {threshold_s}")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib

    def fn(rec: StepRecorder) -> Optional[str]:
        ev = rec.events("step_latency")[-window:]
        if len(ev) < window:
            return None
        h = metrics_lib.Histogram((), metrics_lib.STEP_TIME_EDGES)
        for e in ev:
            h.observe(float(e.data.get("seconds", 0.0)))
        p = h.quantile(q)
        if p > threshold_s:
            return (
                f"step latency p{q * 100:g} over the last {window} steps"
                f" is {p:.3g}s (> {threshold_s:.3g}s SLO)"
            )
        return None

    return HealthRule("slo_latency_p99", ALERT, fn)


def slo_dropped_rows(
    threshold: int = 0, window: int = 16, q: float = 0.99
) -> HealthRule:
    """ALERT when the bucketed ``q``-quantile of rows dropped per step
    over the last ``window`` ``step_latency`` events exceeds
    ``threshold`` — the ``grid_dropped_rows`` histogram's SLO twin of
    :func:`slo_latency_p99` (default 0: any sustained loss breaches)."""
    if threshold < 0:
        raise ValueError(f"threshold must be >= 0, got {threshold}")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib

    def fn(rec: StepRecorder) -> Optional[str]:
        ev = rec.events("step_latency")[-window:]
        if len(ev) < window:
            return None
        h = metrics_lib.Histogram((), metrics_lib.DROPPED_EDGES)
        for e in ev:
            h.observe(int(e.data.get("dropped", 0)))
        p = h.quantile(q)
        if p > threshold:
            return (
                f"dropped rows p{q * 100:g} over the last {window} steps"
                f" is {p:g} (> {threshold} SLO)"
            )
        return None

    return HealthRule("slo_dropped_rows", ALERT, fn)


def _over_budget(h, threshold: float) -> int:
    """Events in buckets strictly above the one containing ``threshold``.

    Bucketed like the quantile rules: an observation only counts as an
    SLO violation once it lands beyond the threshold's own bucket edge,
    so the burn rate trips on the same evidence an operator sees in the
    ``/metrics`` histogram — never on sub-bucket noise the exposition
    cannot show."""
    for le, cum in h.cumulative():
        if le >= threshold:
            return h.count - cum
    return 0  # unreachable: cumulative() ends with the +Inf bucket


def _burn_rate_rule(
    name: str,
    kind_key: str,
    edges,
    cast,
    threshold,
    unit: str,
    objective: float,
    fast_window: int,
    slow_window: int,
    fast_burn: float,
    slow_burn: float,
) -> HealthRule:
    # shared machinery behind burn_rate_latency / burn_rate_dropped
    if not 0.0 < objective < 1.0:
        raise ValueError(f"objective must be in (0, 1), got {objective}")
    if fast_window < 1:
        raise ValueError(f"fast_window must be >= 1, got {fast_window}")
    if slow_window <= fast_window:
        raise ValueError(
            f"slow_window must exceed fast_window "
            f"({slow_window} <= {fast_window})"
        )
    if fast_burn <= 0 or slow_burn <= 0:
        raise ValueError(
            f"burn factors must be > 0, got {fast_burn}/{slow_burn}"
        )
    from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib

    budget = 1.0 - objective

    def fn(rec: StepRecorder) -> Optional[str]:
        ev = rec.events("step_latency")
        # fast window first: it pages at the higher factor, and when both
        # would fire the short window is the fresher evidence
        for label, win, factor in (
            ("fast", fast_window, fast_burn),
            ("slow", slow_window, slow_burn),
        ):
            tail = ev[-win:]
            if len(tail) < win:
                continue  # a cold journal is not a breach
            h = metrics_lib.Histogram((), edges)
            for e in tail:
                h.observe(cast(e.data.get(kind_key, 0)))
            bad = _over_budget(h, threshold)
            burn = (bad / win) / budget
            if burn >= factor:
                return (
                    f"error budget burning at {burn:.1f}x over the "
                    f"{label} window (>= {factor:g}x): {bad}/{win} steps "
                    f"beyond {threshold:g}{unit} against a {budget:.2%} "
                    f"budget (objective {objective:g})"
                )
        return None

    return HealthRule(name, ALERT, fn)


def burn_rate_latency(
    threshold_s: float,
    objective: float = 0.99,
    fast_window: int = 16,
    slow_window: int = 64,
    fast_burn: float = 8.0,
    slow_burn: float = 2.0,
) -> HealthRule:
    """ALERT when the step-latency error budget burns too fast.

    Multi-window burn-rate alerting (the SRE-standard upgrade of the
    point-in-time :func:`slo_latency_p99`): over each window the bad
    fraction is the share of ``step_latency`` events whose seconds land
    beyond ``threshold_s``'s pow2 bucket, and the burn rate is that
    fraction divided by the error budget ``1 - objective``. The *fast*
    window fires at ``fast_burn`` x budget (sudden total breach pages on
    minutes of evidence); the *slow* window fires at ``slow_burn`` x
    (sustained low-grade burn that would quietly exhaust the budget).
    Each window needs to be full before it can fire, and the journaled
    reason names the window and burn factor that tripped."""
    from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib

    return _burn_rate_rule(
        "burn_rate_latency",
        "seconds",
        metrics_lib.STEP_TIME_EDGES,
        float,
        float(threshold_s),
        "s",
        objective,
        fast_window,
        slow_window,
        fast_burn,
        slow_burn,
    )


def burn_rate_dropped(
    threshold: int = 0,
    objective: float = 0.99,
    fast_window: int = 16,
    slow_window: int = 64,
    fast_burn: float = 8.0,
    slow_burn: float = 2.0,
) -> HealthRule:
    """ALERT when the dropped-rows error budget burns too fast — the
    ``grid_dropped_rows`` twin of :func:`burn_rate_latency` (default
    ``threshold=0``: any step that drops rows spends budget)."""
    if threshold < 0:
        raise ValueError(f"threshold must be >= 0, got {threshold}")
    from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib

    return _burn_rate_rule(
        "burn_rate_dropped",
        "dropped",
        metrics_lib.DROPPED_EDGES,
        int,
        float(threshold),
        " rows",
        objective,
        fast_window,
        slow_window,
        fast_burn,
        slow_burn,
    )


def default_rules() -> List[HealthRule]:
    """The stock rule set, in evaluation order. SCHEMA.md's "Health
    rule table" is the documentation twin of this list — name, order
    and severity are asserted equal by the drift test in
    ``tests/test_probes.py``, so a rule added here must land there in
    the same breath (and vice versa)."""
    return [
        backlog_growth(),
        dropped_rows(),
        capacity_grow_frequency(),
        imbalance_ratio(),
        step_time_spike(),
        fast_path_fallback(),
        snapshot_staleness(),
        nan_detected(),
        conservation_drift(),
        bounds_violation(),
    ]


class HealthMonitor:
    """Evaluate declarative rules against a recorder's journal.

    ``monitor.evaluate()`` runs every rule, records one ``alert`` event
    per NEW finding into the same ring (deduplicated: the same
    (rule, reason) pair is not re-journaled until new events arrive),
    invokes the registered callbacks with each new :class:`Finding`, and
    returns ``{"status": OK|WARN|ALERT, "findings": [...]}`` — the dict
    behind ``GridRedistribute.health()``.
    """

    def __init__(
        self,
        recorder: StepRecorder,
        rules: Optional[Sequence[HealthRule]] = None,
        on_alert: Optional[Callable[[Finding], None]] = None,
    ):
        self.recorder = recorder
        self.rules = list(default_rules() if rules is None else rules)
        self.callbacks: List[Callable[[Finding], None]] = []
        if on_alert is not None:
            self.callbacks.append(on_alert)
        # (rule name) -> (reason, journal seq at fire time): dedup state
        self._seen: Dict[str, object] = {}

    def add_callback(self, cb: Callable[[Finding], None]) -> None:
        self.callbacks.append(cb)

    def note_step_time(self, seconds: float) -> None:
        """Journal one measured step time (feeds ``step_time_spike``)."""
        self.recorder.record("step_time", seconds=float(seconds))

    def evaluate(self, record: bool = True) -> Dict[str, object]:
        """Run every rule over the journal; returns the verdict dict.

        ``record=False`` is the scrape path (``/healthz`` in
        ``scripts/metrics_serve.py``): rules run and the verdict is
        returned, but nothing is journaled, no callbacks fire, and the
        dedup state is untouched — an external poller hitting the
        endpoint every few seconds must observe health, not mutate it.
        """
        findings: List[Finding] = []
        # dedup clock: non-meta events ever journaled — the alert /
        # callback_error / incident events an evaluation pass (or its
        # callbacks, e.g. the flight recorder) records must not count as
        # "new evidence" for the next pass, or a standing finding would
        # re-journal itself forever off its own side effects
        rec = self.recorder
        counts = rec.counts()
        seq = rec.total_recorded - sum(
            counts.get(k, 0) for k in _META_KINDS
        )
        for rule in self.rules:
            reason = rule.fn(rec)
            if reason is None:
                if record:
                    self._seen.pop(rule.name, None)
                continue
            f = Finding(rule.name, rule.severity, reason)
            findings.append(f)
            if not record:
                continue
            if self._seen.get(rule.name) == (reason, seq):
                continue  # same finding, no new events: don't re-journal
            rec.record(
                "alert",
                rule=rule.name,
                severity=rule.severity,
                reason=reason,
            )
            self._seen[rule.name] = (reason, seq)
            for cb in self.callbacks:
                # a broken sink must never mask a real ALERT (or abort
                # the rules still unevaluated): journal and keep going
                try:
                    cb(f)
                except Exception as exc:
                    rec.record(
                        "callback_error",
                        rule=rule.name,
                        callback=getattr(cb, "__qualname__", None)
                        or type(cb).__name__,
                        error=f"{type(exc).__name__}: {exc}",
                    )
        status = OK
        for f in findings:
            if _SEVERITY_ORDER[f.severity] > _SEVERITY_ORDER[status]:
                status = f.severity
        return {
            "status": status,
            "findings": [f._asdict() for f in findings],
        }
