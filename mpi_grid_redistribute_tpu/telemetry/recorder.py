"""Step recorder: bounded host-side ring buffer of structured events.

Production systems attribute their own incidents; this is the journal the
rest of the repo writes to. Events are plain host-side dicts — recording
one is a lock-guarded deque append and NEVER syncs the device (the same
contract the deferred overflow checks in :mod:`..api` keep), so the
recorder can stay on in steady-state loops. The ring is bounded (default
4096 events); all-time per-kind counts survive eviction, so ``counts()``
is exact even when the ring has wrapped.

**Locking contract** (racecheck T001/T005, SCHEMA.md "Recorder
locking"): one recorder is shared across threads — the step loop
records while the async snapshot writer exports the journal and the
metrics scrape path snapshots ``events()``/``counts()``. Every mutation
(:meth:`record`, :meth:`record_at`, :meth:`clear`) and every reader of
``_ring``/``_counts``/``_seq`` takes the internal ``_lock``; exports
copy the retained window under the lock and do file I/O outside it
(racecheck T003). The lock is uncontended in steady state, keeping the
per-event cost inside the committed <=2% recorder-overhead budget.

Event kinds emitted by the in-repo instruments:

* ``redistribute`` / ``halo`` — one per public API call (call index,
  capacities, rows).
* ``capacity_grow`` / ``halo_grow`` — a measured overflow grew a
  capacity (old/new values, the measured need that sized the rebuild).
* ``overflow_window_scheduled`` / ``overflow_window_clean`` /
  ``overflow_window_loss`` — the deferred-check lifecycle (SURVEY.md
  §5.3: surfaced, never silent).
* ``migrate_step`` — per-step send/recv/backlog counters from a
  step-stacked ``MigrateStats`` (:func:`record_migrate_steps`).
* ``fast_path`` — per-step sparse-engine routing outcome (taken vs
  dense fallback, mover count vs ``mover_cap``) from
  :func:`record_fast_path_steps` (ISSUE 4).
* ``mover_cap_grow`` — :class:`..api.MoverCapacity` ratcheted the
  sparse engine's mover block (old/new cap, measured peak).
"""

from __future__ import annotations

import collections
import io
import json
import os
import socket
import threading
import time
from typing import Dict, List, NamedTuple, Optional

import numpy as np

from . import context as context_lib


class Event(NamedTuple):
    """One recorded event: monotone sequence number, host wall time
    (``time.time()``), kind tag, and a flat JSON-serializable payload."""

    seq: int
    time: float
    kind: str
    data: dict

    def to_json(self, tags: Optional[dict] = None) -> str:
        """JSON for one journal line; ``tags`` adds envelope fields
        (e.g. the recorder's ``host``/``pid``) without touching the
        payload — payload keys win on collision so replayed journals
        round-trip."""
        doc = {"seq": self.seq, "time": self.time, "kind": self.kind}
        if tags:
            doc.update(tags)
        doc.update(self.data)
        return json.dumps(doc, sort_keys=True)


class StepRecorder:
    """Bounded ring buffer of :class:`Event` with all-time kind counts.

    ``capacity`` bounds retained events (oldest evicted first); the
    per-kind counters in :meth:`counts` are all-time, so operators can
    distinguish "no growth events ever" from "growth events scrolled
    off". ``enabled=False`` turns :meth:`record` into a no-op counter
    bump — the shape of the API stays, the memory goes away.

    ``host``/``pid`` identify the writing process on every exported
    journal line (multi-host shard merging keys on them; see
    :mod:`.aggregate`). They default to this process but are
    overridable — pod emulations on one machine label virtual hosts,
    and shard replay preserves the original writer.
    """

    def __init__(
        self,
        capacity: int = 4096,
        enabled: bool = True,
        host: Optional[str] = None,
        pid: Optional[int] = None,
    ):
        if int(capacity) < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._ring: collections.deque = collections.deque(
            maxlen=int(capacity)
        )
        self._counts: Dict[str, int] = {}
        self._seq = 0
        # guards _ring/_counts/_seq: the step loop records while the
        # snapshot writer exports and the scrape path reads (see the
        # module docstring's locking contract)
        self._lock = threading.Lock()
        self.enabled = bool(enabled)
        self.host = socket.gethostname() if host is None else str(host)
        self.pid = os.getpid() if pid is None else int(pid)

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._ring.maxlen

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    @property
    def evicted(self) -> int:
        """Events recorded but no longer retained (ring wrapped)."""
        with self._lock:
            return self._seq - len(self._ring)

    def record(self, kind: str, **data) -> None:
        """Append one event. Host-side only; values must already be host
        scalars (int/float/str) — pass ``int(...)``/``float(...)`` of any
        device value at a point where syncing is acceptable, or better,
        record only host-derived control-flow facts (capacities, call
        indices, window bounds), which is what the in-repo hooks do."""
        with self._lock:
            self._record_locked(kind, None, data)

    def record_at(self, kind: str, when: Optional[float], **data) -> None:
        """:meth:`record` with an explicit wall time — the replay path.

        Journal rehydration (``scripts/trace_export.py``) and multi-host
        shard merging (:mod:`.aggregate`) re-record events that already
        happened; stamping them with *this* process's clock would destroy
        the cross-shard ordering the merge just computed. ``when=None``
        falls back to ``time.time()`` (same as :meth:`record`)."""
        with self._lock:
            self._record_locked(kind, when, data)

    def _record_locked(
        self, kind: str, when: Optional[float], data: dict
    ) -> None:
        # caller holds self._lock
        self._counts[kind] = self._counts.get(kind, 0) + 1
        self._seq += 1
        if self.enabled:
            # Merge the recording thread's active StepContext into the
            # envelope (telemetry/context.py). Payload keys win: replayed
            # events (record_at from aggregate/trace_export) already carry
            # their original attribution and must not be restamped.
            env = context_lib.envelope_fields()
            if env:
                for k, v in env.items():
                    if k not in data:
                        data[k] = v
            t = time.time() if when is None else float(when)
            self._ring.append(Event(self._seq, t, kind, data))

    def events(self, kind: Optional[str] = None) -> List[Event]:
        """Retained events, oldest first; optionally filtered by kind.
        Returns a snapshot copied under the lock — callers iterate it
        without racing concurrent appends."""
        with self._lock:
            if kind is None:
                return list(self._ring)
            return [e for e in self._ring if e.kind == kind]

    def last(self, kind: Optional[str] = None) -> Optional[Event]:
        evs = self.events(kind)
        return evs[-1] if evs else None

    def counts(self) -> Dict[str, int]:
        """All-time events per kind (survives ring eviction)."""
        with self._lock:
            return dict(self._counts)

    def clear(self) -> None:
        """Drop retained events AND all-time counts (fresh journal)."""
        with self._lock:
            self._ring.clear()
            self._counts.clear()
            self._seq = 0

    def to_jsonl(self, path_or_file) -> int:
        """Write retained events as JSON Lines; returns events written.

        Accepts a path or an open text file. Every line carries the
        recorder's ``host``/``pid`` envelope tags so shards from
        different processes stay attributable after they are merged
        (SCHEMA.md "Envelope"). The export is the retained window only —
        pair with :meth:`counts` (exact all-time totals) when the ring
        may have wrapped.
        """
        events = self.events()
        tags = {"host": self.host, "pid": self.pid}
        if isinstance(path_or_file, (str, bytes)):
            with open(path_or_file, "w") as f:
                for e in events:
                    f.write(e.to_json(tags) + "\n")
        else:
            f = path_or_file
            for e in events:
                f.write(e.to_json(tags) + "\n")
        return len(events)

    def dumps_jsonl(self) -> str:
        buf = io.StringIO()
        self.to_jsonl(buf)
        return buf.getvalue()


def record_migrate_steps(
    recorder: StepRecorder,
    stats,
    max_steps: Optional[int] = None,
    rank_totals: bool = False,
) -> int:
    """Feed a step-stacked ``MigrateStats`` into ``recorder`` as one
    ``migrate_step`` event per step (sent/received/backlog/dropped/
    population totals). This is the bridge from the migrate loops — whose
    stats come back as ``[S, R]`` device arrays — to the host journal;
    calling it forces ONE host transfer of the (tiny) stats pytree, so
    call it where the bench drivers already read stats, not inside a hot
    loop. ``max_steps`` keeps only the trailing window.
    ``rank_totals=True`` additionally records the per-rank vectors
    (``sent_per_rank``/``received_per_rank``/``population_per_rank``
    lists) each step — the per-rank view the flow path's imbalance rules
    consume. Returns the number of events recorded.

    Every counter leaf must have the same shape as ``sent`` — a
    mismatched hand-built pytree raises a named ValueError here instead
    of silently reshaping into wrong per-step totals (or dying in numpy
    with an opaque broadcast error)."""
    sent = np.asarray(stats.sent)
    sent = sent.reshape(-1, sent.shape[-1])
    leaves = {}
    for name in ("received", "backlog", "dropped_recv", "population"):
        a = np.asarray(getattr(stats, name))
        if a.size != sent.size:
            raise ValueError(
                f"MigrateStats.{name} has shape {a.shape} "
                f"({a.size} elements) but sent has shape "
                f"{np.asarray(stats.sent).shape} ({sent.size} elements) "
                f"— stats leaves must be shape-congruent per step"
            )
        leaves[name] = a.reshape(sent.shape)
    recv, backlog = leaves["received"], leaves["backlog"]
    dropped, pop = leaves["dropped_recv"], leaves["population"]
    start = 0 if max_steps is None else max(0, sent.shape[0] - max_steps)
    for s in range(start, sent.shape[0]):
        extra = {}
        if rank_totals:
            extra = {
                "sent_per_rank": [int(x) for x in sent[s]],
                "received_per_rank": [int(x) for x in recv[s]],
                "population_per_rank": [int(x) for x in pop[s]],
            }
        recorder.record(
            "migrate_step",
            step=s,
            sent=int(sent[s].sum()),
            received=int(recv[s].sum()),
            backlog=int(backlog[s].sum()),
            dropped_recv=int(dropped[s].sum()),
            population=int(pop[s].sum()),
            **extra,
        )
    return sent.shape[0] - start


def record_fast_path_steps(
    recorder: StepRecorder,
    stats,
    mover_cap: Optional[int] = None,
    max_steps: Optional[int] = None,
) -> int:
    """Feed a step-stacked ``MigrateStats`` from a sparse-capable engine
    into ``recorder`` as one ``fast_path`` event per step: whether the
    mover-sparse branch ran (``taken``) or the step fell back to the
    dense planar engine, plus the exact mover count that drove the
    routing guard (``movers = sent + backlog`` — granted sends plus
    held-back leavers) and, when given, the static ``mover_cap`` the
    count was checked against. Same host-transfer contract as
    :func:`record_migrate_steps`: call it where the driver already reads
    stats. ``max_steps`` keeps only the trailing window. Returns events
    recorded.

    Raises a named ValueError when ``stats.fast_path`` is None — that
    means the loop was built without ``mover_cap`` and carries no sparse
    path, so journaling a 0% hit rate for it would misread as "always
    falling back"."""
    if stats.fast_path is None:
        raise ValueError(
            "MigrateStats.fast_path is None: this loop was built without"
            " mover_cap (no sparse path to journal); build it with"
            " engine='auto'/'sparse' on a sparse-eligible config first"
        )
    fp = np.asarray(stats.fast_path)
    fp = fp.reshape(-1, fp.shape[-1])
    sent = np.asarray(stats.sent).reshape(fp.shape)
    backlog = np.asarray(stats.backlog).reshape(fp.shape)
    start = 0 if max_steps is None else max(0, fp.shape[0] - max_steps)
    extra = {} if mover_cap is None else {"mover_cap": int(mover_cap)}
    for s in range(start, fp.shape[0]):
        recorder.record(
            "fast_path",
            step=s,
            # the guard is one scalar broadcast across ranks: any() == all()
            taken=int(bool(fp[s].any())),
            movers=int((sent[s] + backlog[s]).sum()),
            movers_max_rank=int((sent[s] + backlog[s]).max()),
            **extra,
        )
    return fp.shape[0] - start


def record_chunk_steps(
    recorder: StepRecorder,
    first_step: int,
    seconds_per_step: float,
    dropped,
) -> int:
    """Fold one resident chunk's scanned ys into the per-step journal
    surface: one ``step_latency`` event per step, with the wall
    apportioned evenly from the chunk dispatch and the dropped-row
    counts taken from the in-graph scan ys (``service/resident.py``).
    Same host-transfer contract as :func:`record_migrate_steps`: the
    caller passes already-fetched host values at a chunk boundary,
    never device arrays from a hot loop. Steps are numbered
    ``first_step, first_step + 1, ...`` — the post-increment numbering
    the eager loop journals — so the SLO window rules and the
    ``grid_step_latency_seconds`` / ``grid_dropped_rows`` histogram
    scrape see an identical event stream for any chunk length. Returns
    the number of events recorded."""
    n = 0
    for i, d in enumerate(dropped):
        recorder.record(
            "step_latency",
            step=int(first_step) + i,
            seconds=float(seconds_per_step),
            dropped=int(d),
        )
        n += 1
    return n


def fast_path_hit_rate(recorder: StepRecorder) -> Optional[float]:
    """Fraction of retained ``fast_path`` events with ``taken=1``; None
    when no sparse-engine steps have been journaled."""
    ev = recorder.events("fast_path")
    if not ev:
        return None
    return sum(int(e.data.get("taken", 0)) for e in ev) / len(ev)
