"""Programmatic profiler sessions (ISSUE 14).

``utils/profiling.trace`` already wraps ``jax.profiler.trace`` for
hand-run chip sessions; this module makes the capture a SERVICE
feature: :class:`ProfilerSession` is a context manager any driver or
CLI can hold around its hot region, gated by configuration
(``DriverConfig.profile_dir`` / the ``GRID_PROFILE_DIR`` env knob) so a
chip session captures traces without code edits, and journaled as a
``profile_session`` event so the capture is discoverable from the
journal alone (trace dir, wall duration, whether the profiler actually
armed).

Failure posture: profiling must never take the service down. A missing
directory knob disables the session outright (no event — the knob IS
the gate); an unavailable/broken ``jax.profiler`` degrades to a no-op
that still journals the attempt with ``armed=False`` and the error
string, because a silently missing trace on a chip session is exactly
the observability gap this subsystem exists to close.
"""

from __future__ import annotations

import os
import time
from typing import Optional

PROFILE_DIR_ENV = "GRID_PROFILE_DIR"


def profile_dir_from_env() -> Optional[str]:
    """The env-side knob (``GRID_PROFILE_DIR``); empty/unset = off."""
    d = os.environ.get(PROFILE_DIR_ENV, "").strip()
    return d or None


class ProfilerSession:
    """Gated ``jax.profiler`` trace session around a code region.

    ``with ProfilerSession(cfg.profile_dir, recorder=rec, label="run"):``
    — when ``log_dir`` is None the env knob is consulted; when both are
    unset the session is a guaranteed no-op (``enabled`` False, nothing
    journaled, jax never imported). Re-entrant use is an error only in
    jax; this wrapper surfaces it as a journaled failed arm, not a
    crash.
    """

    def __init__(
        self,
        log_dir: Optional[str] = None,
        recorder=None,
        label: str = "session",
    ):
        self.log_dir = log_dir if log_dir else profile_dir_from_env()
        self.recorder = recorder
        self.label = label
        self.enabled = self.log_dir is not None
        self.armed = False
        self.error: Optional[str] = None
        self._t0: Optional[float] = None

    def __enter__(self) -> "ProfilerSession":
        if not self.enabled:
            return self
        self._t0 = time.perf_counter()
        try:
            import jax

            jax.profiler.start_trace(self.log_dir)
            self.armed = True
        except Exception as e:  # profiling unavailable: degrade, never die
            self.error = f"{type(e).__name__}: {e}"
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if not self.enabled:
            return False
        duration = time.perf_counter() - (self._t0 or time.perf_counter())
        if self.armed:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                self.error = f"{type(e).__name__}: {e}"
                self.armed = False
        if self.recorder is not None:
            self.recorder.record(
                "profile_session",
                trace_dir=self.log_dir,
                label=self.label,
                duration_s=duration,
                armed=self.armed,
                error=self.error,
            )
        return False
