"""Runtime thread-access sanitizer for the telemetry journal.

racecheck (:mod:`..analysis.racecheck`) proves the locking contract
syntactically; this module checks it DYNAMICALLY, the way the fault
matrix checks the restart policy: :class:`ThreadAccessTracer` arms a
live :class:`~.recorder.StepRecorder` by swapping its ``_lock`` /
``_ring`` / ``_counts`` for traced wrappers, then every touch of the
journal's shared state is logged with the touching thread's identity
and whether the recorder lock was held at that instant. A touch without
the lock is a **violation** — detected deterministically on the first
unguarded access, no race timing required, even in a single-threaded
test (which is what makes it CI-able: strip the lock from one call path
and ``assert_clean()`` fails every run, not one run in fifty).

The tracer journals its own lifecycle into the recorder it audits
(``thread_audit`` events, SCHEMA.md): ``action="arm"`` before the wrap
(so the event itself is recorded untraced) and ``action="disarm"``
after the restore, carrying the audit tallies. An audited run is thus
self-describing — a journal shard shows when the sanitizer was on.

Scope: the tracer audits the recorder's internal mutable state (the
T001 surface the analyzer gates). ``_seq`` is a rebound ``int`` rather
than a mutated object, so it cannot be wrapped the same way; ``_ring``
and ``_counts`` are touched by every mutation path that touches
``_seq``, so coverage is not reduced. Tracing costs one dict append per
access — use in tests, not in steady-state loops.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional

from mpi_grid_redistribute_tpu.telemetry.recorder import StepRecorder


@dataclasses.dataclass(frozen=True)
class ThreadAccess:
    """One audited touch of a traced field."""

    thread_id: int
    thread_name: str
    label: str      # which traced object ("recorder" by default)
    field: str      # "_ring" | "_counts" | "_lock"
    op: str         # "read" | "write" | "acquire" | "release"
    lock_held: bool  # recorder lock owned by the touching thread

    @property
    def is_violation(self) -> bool:
        return self.op in ("read", "write") and not self.lock_held


class _TracedLock:
    """Wraps the recorder's ``threading.Lock`` to track which thread
    owns it (stdlib ``Lock`` has no owner concept; RLock's ``_is_owned``
    is private). Drop-in for ``with``/``acquire``/``release``/
    ``locked``."""

    def __init__(self, inner, tracer: "ThreadAccessTracer"):
        self._inner = inner
        self._tracer = tracer
        self._owner: Optional[int] = None

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._owner = threading.get_ident()
            self._tracer._note("_lock", "acquire", True)
        return got

    def release(self) -> None:
        self._tracer._note("_lock", "release", True)
        self._owner = None
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _TracedDeque(collections.deque):
    """Ring-buffer proxy: every mutation/read is audited. Built as a
    real ``deque`` subclass so ``maxlen`` eviction semantics (the whole
    point of the ring) are inherited, not re-implemented."""

    def __init__(self, items, maxlen, tracer):
        super().__init__(items, maxlen)
        self._tracer = tracer

    def append(self, item):
        self._tracer._note("_ring", "write")
        super().append(item)

    def appendleft(self, item):
        self._tracer._note("_ring", "write")
        super().appendleft(item)

    def clear(self):
        self._tracer._note("_ring", "write")
        super().clear()

    def __iter__(self):
        self._tracer._note("_ring", "read")
        return super().__iter__()

    def __len__(self):
        self._tracer._note("_ring", "read")
        return super().__len__()

    def __getitem__(self, i):
        self._tracer._note("_ring", "read")
        return super().__getitem__(i)


class _TracedDict(dict):
    """Counts proxy: mutators and readers audited. ``clear()`` keeps
    object identity, matching ``StepRecorder.clear``'s contract of
    mutating (never rebinding) ``_counts``."""

    def __init__(self, items, tracer):
        super().__init__(items)
        self._tracer = tracer

    def __setitem__(self, k, v):
        self._tracer._note("_counts", "write")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._tracer._note("_counts", "write")
        super().__delitem__(k)

    def clear(self):
        self._tracer._note("_counts", "write")
        super().clear()

    def update(self, *a, **kw):
        self._tracer._note("_counts", "write")
        super().update(*a, **kw)

    def get(self, k, default=None):
        self._tracer._note("_counts", "read")
        return super().get(k, default)

    def __getitem__(self, k):
        self._tracer._note("_counts", "read")
        return super().__getitem__(k)

    def items(self):
        self._tracer._note("_counts", "read")
        return super().items()

    def keys(self):
        self._tracer._note("_counts", "read")
        return super().keys()

    def values(self):
        self._tracer._note("_counts", "read")
        return super().values()


class ThreadAccessTracer:
    """Field-level runtime sanitizer for one :class:`StepRecorder`.

    Usage (the fault-matrix tests wrap whole scenario replays)::

        tracer = ThreadAccessTracer(rd.telemetry)
        with tracer:
            ...drive steps / snapshots / scrapes concurrently...
        tracer.assert_clean()

    ``violations()`` returns every journal-state touch made without the
    recorder lock; with the shipped locked recorder it is empty no
    matter how the threads interleave, and it is NON-empty on the first
    step if any mutation path loses its ``with self._lock`` — the
    deterministic regression tripwire racecheck's static pass is paired
    with.
    """

    def __init__(self, recorder: StepRecorder, label: str = "recorder"):
        self.recorder = recorder
        self.label = label
        self._accesses: List[ThreadAccess] = []
        self._audit_lock = threading.Lock()
        self._armed = False
        self._muted = False  # True while arm/disarm touch traced state
        self._orig_lock = None
        self._orig_ring = None
        self._orig_counts = None
        self._traced_lock: Optional[_TracedLock] = None

    # called by the traced wrappers on every touch
    def _note(self, field: str, op: str, lock_op: bool = False) -> None:
        if self._muted:
            return
        held = (
            lock_op
            or (
                self._traced_lock is not None
                and self._traced_lock.held_by_me()
            )
        )
        t = threading.current_thread()
        acc = ThreadAccess(
            thread_id=threading.get_ident(),
            thread_name=t.name,
            label=self.label,
            field=field,
            op=op,
            lock_held=held,
        )
        with self._audit_lock:
            self._accesses.append(acc)

    def arm(self) -> "ThreadAccessTracer":
        if self._armed:
            return self
        rec = self.recorder
        # journal BEFORE wrapping: the arm event itself goes through the
        # untraced path, so access tallies start at zero
        rec.record("thread_audit", action="arm", label=self.label)
        self._orig_lock = rec._lock
        self._orig_ring = rec._ring
        self._orig_counts = rec._counts
        self._traced_lock = _TracedLock(rec._lock, self)
        rec._lock = self._traced_lock
        rec._ring = _TracedDeque(
            self._orig_ring, self._orig_ring.maxlen, self
        )
        rec._counts = _TracedDict(self._orig_counts, self)
        self._armed = True
        return self

    def disarm(self) -> "ThreadAccessTracer":
        if not self._armed:
            return self
        rec = self.recorder
        # restore first (carrying state mutated while traced), then
        # journal the tallies through the untraced path; the copy-back
        # reads the traced wrappers, so mute the audit around it
        self._muted = True
        try:
            self._orig_ring.clear()
            self._orig_ring.extend(rec._ring)
            self._orig_counts.clear()
            self._orig_counts.update(rec._counts)
            rec._lock = self._orig_lock
            rec._ring = self._orig_ring
            rec._counts = self._orig_counts
        finally:
            self._muted = False
        self._armed = False
        rec.record(
            "thread_audit",
            action="disarm",
            label=self.label,
            accesses=len(self._accesses),
            violations=len(self.violations()),
            threads=len({a.thread_id for a in self._accesses}),
        )
        return self

    def __enter__(self) -> "ThreadAccessTracer":
        return self.arm()

    def __exit__(self, *exc) -> bool:
        self.disarm()
        return False

    @property
    def accesses(self) -> List[ThreadAccess]:
        with self._audit_lock:
            return list(self._accesses)

    def violations(self) -> List[ThreadAccess]:
        return [a for a in self.accesses if a.is_violation]

    def by_thread(self) -> Dict[str, int]:
        """Access count per thread name — the observed topology, the
        runtime twin of ``racecheck --list-threads``."""
        out: Dict[str, int] = {}
        for a in self.accesses:
            out[a.thread_name] = out.get(a.thread_name, 0) + 1
        return out

    def assert_clean(self) -> None:
        v = self.violations()
        if v:
            lines = "\n".join(
                f"  {a.thread_name}({a.thread_id}): {a.label}."
                f"{a.field} {a.op} WITHOUT the recorder lock"
                for a in v[:10]
            )
            raise AssertionError(
                f"{len(v)} unguarded journal-state access(es) "
                f"detected by ThreadAccessTracer:\n{lines}"
            )
