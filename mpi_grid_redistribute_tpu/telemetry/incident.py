"""Flight recorder: freeze an incident bundle the moment an ALERT fires.

An ``alert`` event in the ring is a timestamp, not an investigation: by
the time someone looks, the journal window that explains it has been
evicted and the registry rebuilt many times. :class:`FlightRecorder`
closes that gap. Registered as a :class:`~.health.HealthMonitor`
callback (see :func:`install`), it reacts to every ALERT finding — and,
via :meth:`FlightRecorder.scan_faults` /
:meth:`FlightRecorder.capture_regression`, to injected faults and bench
REGRESSION labels — by freezing everything an operator needs into one
*incident bundle* directory:

``index.json``
    Trigger (rule / severity / reason / what kind of trigger), capture
    time, the :mod:`.context` step context of the triggering event
    (``trace`` + ``ctx_*`` join keys), all-time event counts, retained
    seq range, and the bundle file list. The machine-readable entry
    point for ``scripts/incident.py`` and ``GET /incidents``.
``journal.jsonl``
    The retained journal window at capture time, one event per line in
    the exact export format of :meth:`~.recorder.StepRecorder.to_jsonl`
    — rehydrates through :mod:`.aggregate` into a Perfetto timeline.
``counts.json`` / ``metrics.prom`` / ``health.json`` / ``flow.json`` /
``env.json``
    All-time per-kind counts, the rendered OpenMetrics exposition, the
    triggering finding plus recent ``alert`` events, the latest
    ``flow_snapshot`` gauges, and :func:`~.regress.env_fingerprint`.

Captures are debounced per rule (``debounce_s``) so a standing ALERT
re-confirmed at every health boundary yields exactly one bundle, and
bounded (``keep``) so the incident directory cannot grow without limit.
Determinism for tests: ``clock`` and ``id_fn`` are injectable, bundle
ids default to a process-local monotone counter (not wall time), and
every JSON artifact is written with sorted keys — two seeded runs
produce byte-identical bundles.

Locking: bundle bookkeeping (debounce clocks, the id counter, the fault
scan cursor) lives behind one lock; file I/O and journal snapshots
happen outside it, so a slow disk never blocks the health pass that
triggered the capture beyond the snapshot cost itself.

This module is on the capture path and must import neither jax nor
numpy; ``tests/test_metrics.py`` loads it standalone and asserts jax
never enters ``sys.modules``.
"""
# gridlint: scrape-path

from __future__ import annotations

import json
import os
import shutil
import threading
import weakref
from typing import Dict, List, Optional

from . import context as context_lib
from . import metrics as metrics_lib

__all__ = ["FlightRecorder", "install", "list_bundles", "load_bundle"]

INDEX_SCHEMA = 1

# Envelope keys that constitute the step context of an event
# (telemetry/context.py; documented in telemetry/SCHEMA.md).
_CTX_KEYS = ("trace", "ctx_step", "ctx_call", "ctx_attempt", "ctx_origin")


def _ctx_of(data) -> Dict[str, object]:
    return {k: data[k] for k in _CTX_KEYS if k in data}


def _dump_json(path: str, doc) -> None:
    # sorted keys + trailing newline: byte-stable across seeded runs
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


class FlightRecorder:
    """Freeze debounced incident bundles from a recorder's journal.

    ``recorder`` is the journal to freeze; ``out_dir`` the bundle root
    (created on first capture). ``debounce_s`` suppresses repeat
    captures of the same rule; ``keep`` bounds retained bundles (oldest
    pruned). ``clock`` (defaults to ``time.time``) and ``id_fn``
    (``(n, rule) -> bundle id``) are injectable so tests pin bytes.
    """

    def __init__(
        self,
        recorder,
        out_dir,
        debounce_s: float = 60.0,
        keep: int = 32,
        clock=None,
        id_fn=None,
    ):
        if debounce_s < 0:
            raise ValueError(f"debounce_s must be >= 0, got {debounce_s}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.recorder = recorder
        self.out_dir = str(out_dir)
        self.debounce_s = float(debounce_s)
        self.keep = int(keep)
        if clock is None:
            import time as _time

            clock = _time.time
        self.clock = clock
        self._id_fn = id_fn
        # guards _last_capture/_n/_fault_seq — the health callback can
        # fire on whichever thread runs evaluate() while the driver's
        # boundary scan runs on another
        self._lock = threading.Lock()
        self._last_capture: Dict[str, float] = {}
        self._n = 0
        self._fault_seq = 0

    # -- trigger entry points -------------------------------------------

    def on_finding(self, finding) -> Optional[str]:
        """Health-callback entry point: capture on ALERT findings.

        Registered via :func:`install`; runs inline in
        ``HealthMonitor.evaluate`` on whatever thread evaluates (the
        journal write below is why that thread is a declared writer).
        Returns the bundle directory, or None (non-ALERT / debounced).
        """
        # racecheck: recorder-writer — capture journals an `incident`
        # event into the ring it freezes
        if getattr(finding, "severity", None) != "ALERT":
            return None
        return self.capture(
            rule=finding.rule,
            reason=finding.reason,
            severity=finding.severity,
            trigger="alert",
        )

    def scan_faults(self) -> List[str]:
        """Capture a bundle per ``fault_injected`` event not yet seen.

        Called from the service driver's boundaries and ``close()`` —
        injected faults that crash the attempt before a health pass
        still leave a bundle behind. Returns new bundle directories.
        """
        events = self.recorder.events("fault_injected")
        with self._lock:
            fresh = [e for e in events if e.seq > self._fault_seq]
            if fresh:
                self._fault_seq = fresh[-1].seq
        made = []
        for e in fresh:
            kind = str(e.data.get("fault", "fault"))
            out = self.capture(
                rule=f"fault_{kind}",
                reason=(
                    f"injected {kind} fault at step {e.data.get('step')}"
                ),
                severity="ALERT",
                trigger="fault",
                event=e,
            )
            if out is not None:
                made.append(out)
        return made

    def capture_regression(self, lines, labels) -> List[str]:
        """Capture on ``regress.classify_capture`` REGRESSION labels.

        ``lines``/``labels`` are the report lines and metric→label map
        the classifier returned; one bundle per regressed metric (rule
        ``regression_<metric>``), debounced like any other rule.
        """
        by_metric = {m for m, lab in dict(labels).items() if lab == "REGRESSION"}
        made = []
        for metric in sorted(by_metric):
            detail = next(
                (ln for ln in lines if metric in ln), f"{metric} regressed"
            )
            out = self.capture(
                rule=f"regression_{metric}",
                reason=detail.strip(),
                severity="ALERT",
                trigger="regression",
            )
            if out is not None:
                made.append(out)
        return made

    # -- the capture itself ---------------------------------------------

    def capture(
        self,
        rule: str,
        reason: str,
        severity: str = "ALERT",
        trigger: str = "alert",
        event=None,
    ) -> Optional[str]:
        """Freeze one bundle now; returns its directory or None when the
        rule is inside its debounce window."""
        now = float(self.clock())
        with self._lock:
            last = self._last_capture.get(rule)
            if last is not None and (now - last) < self.debounce_s:
                return None
            self._last_capture[rule] = now
            self._n += 1
            n = self._n
        bundle_id = (
            self._id_fn(n, rule)
            if self._id_fn is not None
            else f"incident-{n:04d}-{rule}"
        )
        # One journal snapshot feeds every artifact so the bundle is
        # internally consistent; the `incident` event is journaled after
        # the files are written (a bundle never contains its own event).
        rec = self.recorder
        events = rec.events()
        counts = rec.counts()
        ctx = self._trigger_context(events, rule, trigger, event)
        out = os.path.join(self.out_dir, bundle_id)
        os.makedirs(out, exist_ok=True)
        files = []

        path = os.path.join(out, "journal.jsonl")
        tags = {"host": rec.host, "pid": rec.pid}
        with open(path, "w", encoding="utf-8") as fh:
            for e in events:
                fh.write(e.to_json(tags))
                fh.write("\n")
        files.append("journal.jsonl")

        _dump_json(os.path.join(out, "counts.json"), counts)
        files.append("counts.json")

        prom = metrics_lib.render_openmetrics(metrics_lib.from_journal(rec))
        with open(
            os.path.join(out, "metrics.prom"), "w", encoding="utf-8"
        ) as fh:
            fh.write(prom)
        files.append("metrics.prom")

        alerts = [
            {"seq": e.seq, "time": e.time, **e.data}
            for e in events
            if e.kind == "alert"
        ][-16:]
        _dump_json(
            os.path.join(out, "health.json"),
            {
                "trigger": {
                    "rule": rule,
                    "severity": severity,
                    "reason": reason,
                },
                "recent_alerts": alerts,
            },
        )
        files.append("health.json")

        flow = next(
            (e for e in reversed(events) if e.kind == "flow_snapshot"), None
        )
        if flow is not None:
            _dump_json(
                os.path.join(out, "flow.json"),
                {"seq": flow.seq, "time": flow.time, **flow.data},
            )
            files.append("flow.json")

        _dump_json(os.path.join(out, "env.json"), self._env())
        files.append("env.json")

        _dump_json(
            os.path.join(out, "index.json"),
            {
                "schema": INDEX_SCHEMA,
                "id": bundle_id,
                "rule": rule,
                "severity": severity,
                "reason": reason,
                "trigger": trigger,
                "captured_at": now,
                "context": ctx,
                "counts": counts,
                "events_retained": len(events),
                "seq_first": events[0].seq if events else 0,
                "seq_last": events[-1].seq if events else 0,
                "files": sorted(files),
            },
        )

        # record_at with the (injectable) capture clock, and the bundle
        # id rather than its absolute path: a later bundle's journal
        # window contains this event, and it must stay byte-stable
        # across seeded runs that use different output roots
        rec.record_at(
            "incident",
            now,
            rule=rule,
            trigger=trigger,
            id=bundle_id,
            events=len(events),
        )
        self._prune()
        return out

    def _trigger_context(self, events, rule, trigger, event):
        # precedence: the triggering event itself, then the alert event
        # this finding just journaled (it carries the evaluating
        # thread's envelope), then whatever context is active here
        if event is not None:
            return _ctx_of(event.data)
        if trigger == "alert":
            for e in reversed(events):
                if e.kind == "alert" and e.data.get("rule") == rule:
                    ctx = _ctx_of(e.data)
                    if ctx:
                        return ctx
                    break
        env = context_lib.envelope_fields()
        return _ctx_of(env) if env else {}

    def _env(self):
        # lazy: regress is jax-free but pulls glob/argparse machinery
        # the hot path never needs
        from . import regress as regress_lib

        try:
            return regress_lib.env_fingerprint()
        except Exception as exc:  # fingerprinting must never kill capture
            return {"error": f"{type(exc).__name__}: {exc}"}

    def _prune(self) -> None:
        bundles = []
        try:
            names = os.listdir(self.out_dir)
        except OSError:
            return
        for name in names:
            d = os.path.join(self.out_dir, name)
            if os.path.isfile(os.path.join(d, "index.json")):
                try:
                    bundles.append((os.path.getmtime(d), name, d))
                except OSError:
                    continue
        bundles.sort()
        for _, _, d in bundles[: max(0, len(bundles) - self.keep)]:
            shutil.rmtree(d, ignore_errors=True)


# recorder -> FlightRecorder already attached to it: a supervisor
# restart builds a fresh driver + monitor around the SAME recorder, and
# the bundle counter / debounce clocks must survive that or every
# attempt would re-capture (and overwrite) the same standing alert.
_INSTALLED: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def install(
    monitor,
    recorder,
    out_dir,
    debounce_s: float = 60.0,
    keep: int = 32,
    clock=None,
    id_fn=None,
) -> FlightRecorder:
    """Attach a :class:`FlightRecorder` to ``monitor`` as an ALERT sink.

    Idempotent per recorder: if a flight recorder for the same
    ``out_dir`` is already attached to this journal (a previous restart
    attempt installed it), it is re-registered on the new monitor and
    its debounce/counter state carries over.
    """
    fr = _INSTALLED.get(recorder)
    if fr is None or fr.out_dir != str(out_dir):
        fr = FlightRecorder(
            recorder,
            out_dir,
            debounce_s=debounce_s,
            keep=keep,
            clock=clock,
            id_fn=id_fn,
        )
        _INSTALLED[recorder] = fr
    if not any(
        getattr(cb, "__self__", None) is fr for cb in monitor.callbacks
    ):
        monitor.add_callback(fr.on_finding)
    return fr


def list_bundles(out_dir) -> List[dict]:
    """Index entries of every bundle under ``out_dir``, oldest first.

    Unreadable bundles are reported as ``{"id", "error"}`` entries
    rather than hidden — a corrupt bundle during an incident is itself
    a finding. Missing directories yield an empty list.
    """
    out_dir = str(out_dir)
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        return []
    entries = []
    for name in names:
        path = os.path.join(out_dir, name, "index.json")
        if not os.path.isfile(path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as fh:
                entries.append(json.load(fh))
        except (OSError, ValueError) as exc:
            entries.append(
                {"id": name, "error": f"{type(exc).__name__}: {exc}"}
            )
    entries.sort(key=lambda d: (d.get("captured_at", 0.0), d.get("id", "")))
    return entries


def load_bundle(out_dir, bundle_id) -> dict:
    """One bundle's index plus its on-disk location and actual files."""
    d = os.path.join(str(out_dir), str(bundle_id))
    path = os.path.join(d, "index.json")
    with open(path, "r", encoding="utf-8") as fh:
        index = json.load(fh)
    index["dir"] = d
    index["files_present"] = sorted(
        f for f in os.listdir(d) if os.path.isfile(os.path.join(d, f))
    )
    return index
