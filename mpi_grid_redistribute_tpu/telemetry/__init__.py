"""Unified telemetry: the always-on observability layer (SURVEY.md §5.1/§5.5).

Turns the scattered instruments that grew around the engines — the
scan-differencing timers in :mod:`..utils.profiling`, the stats summaries
in :mod:`..utils.stats`, the per-op knockout scripts — into one subsystem
with four pieces:

* :mod:`.recorder` — a bounded host-side ring buffer of structured events
  (capacity growth, overflow window scheduling/resolution, halo cap
  growth, per-step exchange counters) with JSONL export. Every
  :class:`~..api.GridRedistribute` owns one as ``rd.telemetry``.
* :mod:`.phases` — reusable phase attribution: ``attribute_phases()``
  wraps the knockout/scan-differencing technique behind one API, and
  ``span()``/``traced_span()`` label host regions (Perfetto
  ``TraceAnnotation``) and traced regions (``jax.named_scope`` → XLA op
  metadata) so profiles read as bin/pack/exchange/unpack, not op soup.
* :mod:`.report` — the metrics surface: one merged dict (stats summary,
  exchange bytes/step, achieved GB/s, ``bw_util`` against the HBM/ICI
  roofs in :mod:`..utils.profiling`, growth/overflow event counts),
  reachable as ``rd.report()`` and emitted by every bench driver.
* :mod:`.regress` — the regression guard: min-of-k timing protocol with
  spread reporting plus a checker comparing a bench capture against the
  committed ``BENCH_r*.json`` history, failing loudly (exit code + report
  line) on >10% regressions (``make bench-check``).

The grid observatory (PR 3) adds three layers on that substrate:

* :mod:`.flow` — per-link flow attribution: the in-graph ``[R, R]``
  flow matrix both engines stack into their stats pytrees,
  :class:`~.flow.FlowAccumulator` host gauges (EMA + cumulative +
  imbalance + hot pairs), ``flow_snapshot`` journal events, per-link
  ``bw_util`` in :func:`~.report.exchange_report`.
* :mod:`.health` — an always-on :class:`~.health.HealthMonitor`
  evaluating declarative rules (backlog growth, dropped rows, grow
  frequency, imbalance, step-time spikes) over the journal; findings
  fire callbacks and land as ``alert`` events in the same ring.
* :mod:`.traceview` — Perfetto/Chrome-trace JSON export of the journal,
  phase attributions and migrate counter tracks
  (``scripts/trace_export.py``; ``rd.to_perfetto()``).

The metrics plane (ISSUE 5) makes the journal scrapable pod-wide:

* :mod:`.metrics` — Counter/Gauge/Histogram (pow2 buckets) registry,
  ``from_journal()`` replay into standard grid families, OpenMetrics
  text rendering (``render_openmetrics``); served live by
  ``scripts/metrics_serve.py`` (``/metrics`` + ``/healthz``) and
  reachable as ``rd.metrics()``.
* :mod:`.aggregate` — multi-host journal aggregation:
  ``merge_journals()`` k-way merges per-process JSONL shards
  (``host``/``pid``-tagged lines) with monotone-repaired clock
  alignment; the :class:`~.aggregate.MergedJournal` projects back into
  a pod-wide recorder, ``MigrateStats``-shaped pod stats for
  :func:`~.report.exchange_report`, and merged flow gauges.
* :mod:`.regress` additionally grew the noise-aware classifier
  (``classify_capture`` — WOBBLE/WARN/REGRESSION against the captures'
  own min-of-k spreads) and ``env_fingerprint()``.

The roofline observatory (ISSUE 14) closes the predicted-vs-achieved
loop:

* :mod:`.roofline` — per-program analytic rooflines from XLA's own cost
  model (``Compiled.cost_analysis()`` FLOPs / bytes over the chip roofs
  in :mod:`..utils.profiling`), cross-checked against the J004/S004
  static wire model with discrepancies journaled as ``roofline`` events
  (``scripts/attribution.py`` is the CLI).
* :mod:`.profiler` — :class:`~.profiler.ProfilerSession`, the gated
  programmatic ``jax.profiler`` trace wrapper (``GRID_PROFILE_DIR`` /
  ``DriverConfig.profile_dir``), journaled as ``profile_session``
  events.

The incident observatory (ISSUE 17) makes the journal causal and the
alerts actionable:

* :mod:`.context` — thread-local :class:`~.context.StepContext`
  (trace id, step/call index, restart attempt, origin thread) merged
  into every event envelope by the recorder; "which step caused this
  alert/restart" becomes a join on ``trace``/``ctx_*`` fields.
* :mod:`.incident` — the :class:`~.incident.FlightRecorder` health
  callback: on ALERT (or injected fault, or bench REGRESSION) it
  freezes a debounced incident bundle — journal window, counts,
  OpenMetrics text, health findings, flow snapshot, env fingerprint,
  triggering step context — under an ``index.json``
  (``scripts/incident.py`` CLI; ``GET /incidents`` on the metrics
  server).
* :mod:`.health` additionally grew multi-window error-budget burn-rate
  rules (``burn_rate_latency`` / ``burn_rate_dropped``) and isolates
  callback exceptions (``callback_error`` events).

The telemetry history plane (ISSUE 18) makes the journal durable and
queryable:

* :mod:`.store` — :class:`~.store.JournalStore`, a segmented
  append-only store the service driver drains the recorder ring into
  at every chunk/health boundary: size/step rotation, sha256-manifest
  integrity (checkpoint staged-rename publishes), age/byte retention,
  and compaction of old raw segments into exact ``store_window``
  summaries (per-kind counts + quantile sketches on the live Histogram
  edges) — bounded disk with byte-exact all-time counts after ring
  eviction (:class:`~.store.StoreReader`; ``scripts/storecheck.py``
  gates ST01–ST07).
* :mod:`.query` — the jax-free query plane over any journal source
  (live recorder, merged shards, store): kind/step/trace/host/ctx
  filters, windowed aggregations (rate, p50/p99, EMA), group-bys —
  served as ``GET /query`` plus the cursor-resumable ``GET /events``
  long-poll on ``scripts/metrics_serve.py``; ``scripts/grid_top.py``
  is the live terminal dashboard and ``scripts/history.py`` the
  cross-run index.

The state-health observatory (ISSUE 20) watches the *physics*, not
just the system:

* :mod:`.probes` — the host side of the in-graph invariant probes
  (``ops/statehealth.py``): :class:`~.probes.ProbeConfig` (static
  off/counters/moments tier; off is bit-identical zero-cost),
  ``record_probe_steps`` journaling one ``state_health`` event per
  scanned step (NaN/Inf rows, out-of-bounds positions, the exact int32
  conservation residual, optional moments), and ``summarize_host``,
  the counter-exact numpy mirror for the driver's eager path.
* :mod:`.health` additionally grew the ``nan_detected`` /
  ``conservation_drift`` / ``bounds_violation`` ALERT rules; the
  driver's boundary gate turns their findings into a
  ``StateCorruptionError`` restart BEFORE the snapshot hook, so the
  supervisor restores a pre-corruption snapshot.

Event schema and metric families: ``telemetry/SCHEMA.md``.
"""

from mpi_grid_redistribute_tpu.telemetry.recorder import (  # noqa: F401
    Event,
    StepRecorder,
    fast_path_hit_rate,
    record_chunk_steps,
    record_fast_path_steps,
    record_migrate_steps,
)
from mpi_grid_redistribute_tpu.telemetry.phases import (  # noqa: F401
    PhaseTiming,
    attribute_phases,
    format_phase_table,
    span,
    traced_span,
)
from mpi_grid_redistribute_tpu.telemetry.report import (  # noqa: F401
    exchange_report,
    row_bytes_of,
)
from mpi_grid_redistribute_tpu.telemetry.regress import (  # noqa: F401
    check_capture,
    classify_capture,
    classify_delta,
    env_fingerprint,
    extract_metrics,
    min_of_k,
    noise_floor,
)
from mpi_grid_redistribute_tpu.telemetry.metrics import (  # noqa: F401
    MetricsRegistry,
    from_journal,
    pow2_edges,
    render_openmetrics,
)
from mpi_grid_redistribute_tpu.telemetry.aggregate import (  # noqa: F401
    MergedJournal,
    merge_journals,
)
from mpi_grid_redistribute_tpu.telemetry.flow import (  # noqa: F401
    FlowAccumulator,
    flow_matrix_of,
    link_report,
    record_flow_snapshot,
)
from mpi_grid_redistribute_tpu.telemetry.health import (  # noqa: F401
    Finding,
    HealthMonitor,
    HealthRule,
    bounds_violation,
    burn_rate_dropped,
    burn_rate_latency,
    conservation_drift,
    default_rules,
    fast_path_fallback,
    nan_detected,
    snapshot_staleness,
)
from mpi_grid_redistribute_tpu.telemetry.probes import (  # noqa: F401
    ProbeConfig,
    record_probe_steps,
    summarize_host,
)
from mpi_grid_redistribute_tpu.telemetry.context import (  # noqa: F401
    StepContext,
)
from mpi_grid_redistribute_tpu.telemetry.incident import (  # noqa: F401
    FlightRecorder,
    list_bundles,
    load_bundle,
)
from mpi_grid_redistribute_tpu.telemetry.traceview import (  # noqa: F401
    to_chrome_trace,
    write_trace,
)
from mpi_grid_redistribute_tpu.telemetry.roofline import (  # noqa: F401
    format_roofline_table,
    roofline_report,
)
from mpi_grid_redistribute_tpu.telemetry.profiler import (  # noqa: F401
    ProfilerSession,
)
from mpi_grid_redistribute_tpu.telemetry.tsan import (  # noqa: F401
    ThreadAccess,
    ThreadAccessTracer,
)
from mpi_grid_redistribute_tpu.telemetry.store import (  # noqa: F401
    JournalStore,
    StoreCorruptError,
    StoreReader,
    list_stores,
)
from mpi_grid_redistribute_tpu.telemetry.query import (  # noqa: F401
    QueryError,
    events_page,
    filter_rows,
    group_rows,
    rows_of,
    run_query,
    window_aggregate,
)
