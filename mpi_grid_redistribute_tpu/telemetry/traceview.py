"""Perfetto/Chrome-trace export of the telemetry journal.

One command turns any bench run into a viewable timeline: the JSON this
module emits loads in Perfetto (ui.perfetto.dev) or ``chrome://tracing``
— the standard Trace Event Format (``{"traceEvents": [...]}``, each
event carrying ``ph``/``ts``/``pid``/``tid``/``name``).

Three track families:

* **Journal instants** (pid 0): every retained
  :class:`~.recorder.StepRecorder` event becomes an instant event
  (``ph="i"``) on a per-kind track (one ``tid`` per event kind, labeled
  with thread-name metadata), timestamped with the event's host wall
  time relative to the first retained event. ``alert`` events land on
  their own track next to the events that caused them. Events whose
  envelope carries a ``trace`` step context (``telemetry/context.py``)
  additionally get Perfetto **flow arrows** (``ph="s"``/``ph="f"``):
  each ``alert`` / ``restart`` / ``incident`` instant is linked back to
  the latest preceding same-trace cause event, so the UI draws the
  arrow from the step that burned the budget to the alert it tripped.
* **Phase spans** (pid 1): :class:`~.phases.PhaseTiming` rows (the
  knockout / ``attribute_phases`` output) become duration events
  (``ph="X"``) laid end to end — each phase's span length is its
  attributed ``delta_s``, so the lane reads as one step's time budget.
* **Migrate counters** (pid 2): ``migrate_step`` journal events become
  counter tracks (``ph="C"``) for population, backlog, sent — the
  timeline view of the drift workload unbalancing. When the journal
  carries measured ``step_time`` events their host wall times anchor
  the counter axis (an honest axis for driver runs, which journal step
  timings at health boundaries); otherwise the axis is SYNTHETIC:
  ``step * step_seconds`` (default 1 ms per step), since batch-journaled
  step events all share one wall time.

``scripts/trace_export.py`` is the CLI wrapper;
``GridRedistribute.to_perfetto()`` exports an API instance's journal.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

_TRACK_FAMILIES = {
    0: "journal (instant events per kind)",
    1: "phase attribution (duration events)",
    2: "migrate steps (counter tracks)",
}

# pid-0 instants that are *reactions* — flow-arrow targets. They (plus
# callback_error, another meta kind) never act as flow *sources*: the
# arrow should point at the workload event that caused the reaction,
# not at an earlier reaction that shares its trace.
_EFFECT_KINDS = ("alert", "restart", "incident")


def _meta(pid: int, tid: int, what: str, name: str) -> Dict[str, object]:
    return {
        "ph": "M",
        "pid": pid,
        "tid": tid,
        "name": what,
        "args": {"name": name},
    }


def _json_safe(v):
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


def to_chrome_trace(
    recorder=None,
    phase_timings: Optional[Sequence] = None,
    step_seconds: Optional[float] = None,
    annotations: Optional[Dict[str, dict]] = None,
) -> Dict[str, object]:
    """Build one Trace Event Format dict from telemetry sources.

    Args:
      recorder: a :class:`~.recorder.StepRecorder`; its retained events
        become instant events (pid 0) and its ``migrate_step`` events
        additionally feed the counter tracks (pid 2).
      phase_timings: :class:`~.phases.PhaseTiming` rows
        (``attribute_phases`` output) for the duration lane (pid 1).
      step_seconds: honest per-step seconds for the counter track's
        synthetic time axis (default 1 ms per step).
      annotations: optional ``{phase_name: {key: value}}`` cost context
        (roofline flops/bytes/bound-by — see ``telemetry.roofline``)
        merged into the matching pid-1 duration event's ``args`` so the
        Perfetto tooltip shows what the phase SHOULD cost next to what
        it measured. Keys never overwrite the measured columns.

    Returns a JSON-serializable dict; every event carries the required
    ``ph``/``ts``/``pid`` keys (schema-checked in ``tests/test_flow.py``).
    """
    events: List[Dict[str, object]] = []
    for pid, name in _TRACK_FAMILIES.items():
        events.append(_meta(pid, 0, "process_name", name))

    # --- pid 0: journal instants, one tid per kind --------------------
    if recorder is not None:
        journal = recorder.events()
        t0 = journal[0].time if journal else 0.0
        tids: Dict[str, int] = {}
        inst_ts: List[float] = []
        for e in journal:
            tid = tids.setdefault(e.kind, len(tids))
            ts = (e.time - t0) * 1e6  # us
            inst_ts.append(ts)
            events.append(
                {
                    "name": e.kind,
                    "ph": "i",
                    "ts": ts,
                    "pid": 0,
                    "tid": tid,
                    "s": "t",  # thread-scoped instant
                    "args": {
                        "seq": e.seq,
                        **{k: _json_safe(v) for k, v in e.data.items()},
                    },
                }
            )
        for kind, tid in tids.items():
            events.append(_meta(0, tid, "thread_name", kind))

        # flow arrows: each effect instant (alert/restart/incident) is
        # linked to the latest preceding same-trace cause event via a
        # ph="s"/"f" pair sharing an id — Perfetto draws the arrow
        flow_id = 0
        last_by_trace: Dict[str, int] = {}
        for i, e in enumerate(journal):
            trace = e.data.get("trace")
            if not isinstance(trace, str):
                continue
            if e.kind in _EFFECT_KINDS:
                j = last_by_trace.get(trace)
                if j is not None:
                    flow_id += 1
                    cause = journal[j]
                    pair = (
                        ("s", j, cause.kind, {}),
                        ("f", i, e.kind, {"bp": "e"}),
                    )
                    for ph, idx, kind, extra in pair:
                        events.append(
                            {
                                "name": f"cause:{e.kind}",
                                "cat": "causal",
                                "ph": ph,
                                "id": flow_id,
                                "ts": inst_ts[idx],
                                "pid": 0,
                                "tid": tids[kind],
                                **extra,
                            }
                        )
            elif e.kind != "callback_error":
                last_by_trace[trace] = i

    # --- pid 1: phase-attribution duration lane -----------------------
    if phase_timings:
        events.append(_meta(1, 0, "thread_name", "phases"))
        cursor = 0.0
        for row in phase_timings:
            dur = max(float(row.delta_s), 0.0) * 1e6
            args: Dict[str, object] = {
                "cumulative_s": float(row.cumulative_s),
                "delta_s": float(row.delta_s),
            }
            if getattr(row, "logical_bytes", None) is not None:
                args["logical_bytes"] = int(row.logical_bytes)
            x = getattr(row, "x_roofline", None)
            if x is not None:
                args["x_roofline"] = float(x)
            extra = (annotations or {}).get(str(row.phase))
            if extra:
                for k, v in extra.items():
                    args.setdefault(str(k), _json_safe(v))
            events.append(
                {
                    "name": str(row.phase),
                    "ph": "X",
                    "ts": cursor,
                    "dur": dur,
                    "pid": 1,
                    "tid": 0,
                    "args": args,
                }
            )
            cursor += dur

    # --- pid 2: migrate-step counter tracks ---------------------------
    if recorder is not None:
        dt_us = (step_seconds if step_seconds else 1e-3) * 1e6
        events.append(_meta(2, 0, "thread_name", "migrate counters"))
        # measured step_time wall times anchor the axis when present;
        # step-keyed where the events carry a step index, positional
        # otherwise. Batch-journaled runs without timings keep the
        # synthetic step * step_seconds axis.
        st = recorder.events("step_time")
        wall_by_step = {
            int(e.data["step"]): e.time for e in st if "step" in e.data
        }
        walls = [e.time for e in st]
        for i, e in enumerate(recorder.events("migrate_step")):
            step = int(e.data.get("step", 0))
            if step in wall_by_step:
                ts = (wall_by_step[step] - t0) * 1e6
            elif walls:
                ts = (walls[min(i, len(walls) - 1)] - t0) * 1e6
            else:
                ts = float(step) * dt_us
            for counter in ("population", "backlog", "sent"):
                if counter in e.data:
                    events.append(
                        {
                            "name": counter,
                            "ph": "C",
                            "ts": ts,
                            "pid": 2,
                            "tid": 0,
                            "args": {counter: int(e.data[counter])},
                        }
                    )

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace(
    path: str,
    recorder=None,
    phase_timings: Optional[Sequence] = None,
    step_seconds: Optional[float] = None,
    annotations: Optional[Dict[str, dict]] = None,
) -> int:
    """Write :func:`to_chrome_trace` JSON to ``path``; returns the number
    of trace events written (metadata included)."""
    trace = to_chrome_trace(
        recorder,
        phase_timings=phase_timings,
        step_seconds=step_seconds,
        annotations=annotations,
    )
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
