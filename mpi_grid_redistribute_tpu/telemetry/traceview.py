"""Perfetto/Chrome-trace export of the telemetry journal.

One command turns any bench run into a viewable timeline: the JSON this
module emits loads in Perfetto (ui.perfetto.dev) or ``chrome://tracing``
— the standard Trace Event Format (``{"traceEvents": [...]}``, each
event carrying ``ph``/``ts``/``pid``/``tid``/``name``).

Three track families:

* **Journal instants** (pid 0): every retained
  :class:`~.recorder.StepRecorder` event becomes an instant event
  (``ph="i"``) on a per-kind track (one ``tid`` per event kind, labeled
  with thread-name metadata), timestamped with the event's host wall
  time relative to the first retained event. ``alert`` events land on
  their own track next to the events that caused them.
* **Phase spans** (pid 1): :class:`~.phases.PhaseTiming` rows (the
  knockout / ``attribute_phases`` output) become duration events
  (``ph="X"``) laid end to end — each phase's span length is its
  attributed ``delta_s``, so the lane reads as one step's time budget.
* **Migrate counters** (pid 2): ``migrate_step`` journal events become
  counter tracks (``ph="C"``) for population, backlog, sent — the
  timeline view of the drift workload unbalancing. Step events are
  journaled in one batch (their wall times are all equal), so this
  track uses SYNTHETIC time: ``step * step_seconds`` (default 1 ms per
  step; pass the measured per-step seconds for an honest axis).

``scripts/trace_export.py`` is the CLI wrapper;
``GridRedistribute.to_perfetto()`` exports an API instance's journal.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

_TRACK_FAMILIES = {
    0: "journal (instant events per kind)",
    1: "phase attribution (duration events)",
    2: "migrate steps (counter tracks, synthetic time)",
}


def _meta(pid: int, tid: int, what: str, name: str) -> Dict[str, object]:
    return {
        "ph": "M",
        "pid": pid,
        "tid": tid,
        "name": what,
        "args": {"name": name},
    }


def _json_safe(v):
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


def to_chrome_trace(
    recorder=None,
    phase_timings: Optional[Sequence] = None,
    step_seconds: Optional[float] = None,
    annotations: Optional[Dict[str, dict]] = None,
) -> Dict[str, object]:
    """Build one Trace Event Format dict from telemetry sources.

    Args:
      recorder: a :class:`~.recorder.StepRecorder`; its retained events
        become instant events (pid 0) and its ``migrate_step`` events
        additionally feed the counter tracks (pid 2).
      phase_timings: :class:`~.phases.PhaseTiming` rows
        (``attribute_phases`` output) for the duration lane (pid 1).
      step_seconds: honest per-step seconds for the counter track's
        synthetic time axis (default 1 ms per step).
      annotations: optional ``{phase_name: {key: value}}`` cost context
        (roofline flops/bytes/bound-by — see ``telemetry.roofline``)
        merged into the matching pid-1 duration event's ``args`` so the
        Perfetto tooltip shows what the phase SHOULD cost next to what
        it measured. Keys never overwrite the measured columns.

    Returns a JSON-serializable dict; every event carries the required
    ``ph``/``ts``/``pid`` keys (schema-checked in ``tests/test_flow.py``).
    """
    events: List[Dict[str, object]] = []
    for pid, name in _TRACK_FAMILIES.items():
        events.append(_meta(pid, 0, "process_name", name))

    # --- pid 0: journal instants, one tid per kind --------------------
    if recorder is not None:
        journal = recorder.events()
        t0 = journal[0].time if journal else 0.0
        tids: Dict[str, int] = {}
        for e in journal:
            tid = tids.setdefault(e.kind, len(tids))
            events.append(
                {
                    "name": e.kind,
                    "ph": "i",
                    "ts": (e.time - t0) * 1e6,  # us
                    "pid": 0,
                    "tid": tid,
                    "s": "t",  # thread-scoped instant
                    "args": {
                        "seq": e.seq,
                        **{k: _json_safe(v) for k, v in e.data.items()},
                    },
                }
            )
        for kind, tid in tids.items():
            events.append(_meta(0, tid, "thread_name", kind))

    # --- pid 1: phase-attribution duration lane -----------------------
    if phase_timings:
        events.append(_meta(1, 0, "thread_name", "phases"))
        cursor = 0.0
        for row in phase_timings:
            dur = max(float(row.delta_s), 0.0) * 1e6
            args: Dict[str, object] = {
                "cumulative_s": float(row.cumulative_s),
                "delta_s": float(row.delta_s),
            }
            if getattr(row, "logical_bytes", None) is not None:
                args["logical_bytes"] = int(row.logical_bytes)
            x = getattr(row, "x_roofline", None)
            if x is not None:
                args["x_roofline"] = float(x)
            extra = (annotations or {}).get(str(row.phase))
            if extra:
                for k, v in extra.items():
                    args.setdefault(str(k), _json_safe(v))
            events.append(
                {
                    "name": str(row.phase),
                    "ph": "X",
                    "ts": cursor,
                    "dur": dur,
                    "pid": 1,
                    "tid": 0,
                    "args": args,
                }
            )
            cursor += dur

    # --- pid 2: migrate-step counter tracks (synthetic time) ----------
    if recorder is not None:
        dt_us = (step_seconds if step_seconds else 1e-3) * 1e6
        events.append(_meta(2, 0, "thread_name", "migrate counters"))
        for e in recorder.events("migrate_step"):
            ts = float(e.data.get("step", 0)) * dt_us
            for counter in ("population", "backlog", "sent"):
                if counter in e.data:
                    events.append(
                        {
                            "name": counter,
                            "ph": "C",
                            "ts": ts,
                            "pid": 2,
                            "tid": 0,
                            "args": {counter: int(e.data[counter])},
                        }
                    )

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace(
    path: str,
    recorder=None,
    phase_timings: Optional[Sequence] = None,
    step_seconds: Optional[float] = None,
    annotations: Optional[Dict[str, dict]] = None,
) -> int:
    """Write :func:`to_chrome_trace` JSON to ``path``; returns the number
    of trace events written (metadata included)."""
    trace = to_chrome_trace(
        recorder,
        phase_timings=phase_timings,
        step_seconds=step_seconds,
        annotations=annotations,
    )
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
