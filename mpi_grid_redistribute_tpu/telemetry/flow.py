"""Per-link flow attribution: who sends how much to whom (SURVEY.md §5.5).

The engine's whole job is moving rows between ranks, yet until this
module the observable surface was *aggregate* motion only (summed
sent/received per step). The flow matrix closes that gap:

* **In-graph capture** costs nothing extra: both migrate engines already
  compute the granted per-(source, dest) send-count table for their pack
  phase, and ``MigrateStats.flow`` simply stacks it into the stats
  pytree (``[R, R]`` int32 per step, entry ``[i, j]`` = rows rank ``i``
  sent rank ``j``). ``RedistributeStats.send_counts`` has carried the
  same matrix since the seed. No collective is added, no host sync
  happens inside the step — the matrix rides the same device->host read
  the bench drivers already do for ``sent``/``received``.
* :func:`flow_matrix_of` normalizes either stats pytree to a step-major
  ``[S, R, R]`` host array.
* :class:`FlowAccumulator` is the host-side gauge: cumulative matrix,
  per-step EMA, population-imbalance gauge (max/mean), top-k hot pairs.
* :func:`record_flow_snapshot` journals a compact ``flow_snapshot``
  event (totals + imbalance + hot pairs, never the full matrix) into a
  :class:`~.recorder.StepRecorder`, where :mod:`.health` rules and the
  trace export can see it.
* :func:`link_report` turns per-pair rows into per-link moved bytes and
  bandwidth utilization — the per-link refinement of
  :func:`.report.exchange_report`'s aggregate ``bw_util``.

Row sums of the matrix equal ``sent`` and column sums equal
``received`` exactly (sends are receiver-granted, so both sides agree
by construction; tested in ``tests/test_flow.py``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from mpi_grid_redistribute_tpu.utils import profiling


def flow_matrix_of(stats) -> np.ndarray:
    """Normalize a stats pytree to a step-major ``[S, R, R]`` flow array.

    Accepts a ``MigrateStats`` (uses the ``flow`` leaf) or a
    ``RedistributeStats`` (uses ``send_counts``), single-call or
    step-stacked. Returns int64 (cumulative sums of int32 matrices can
    overflow at production step counts).
    """
    if hasattr(stats, "flow"):
        if stats.flow is None:
            raise ValueError(
                "MigrateStats.flow is None: this stats pytree predates "
                "the flow capture (hand-built fixture?) — the engines "
                "always populate it"
            )
        m = np.asarray(stats.flow)
    elif hasattr(stats, "send_counts"):
        m = np.asarray(stats.send_counts)
    else:
        raise TypeError(
            f"expected MigrateStats or RedistributeStats, got "
            f"{type(stats).__name__}"
        )
    if m.ndim < 2 or m.shape[-1] != m.shape[-2]:
        raise ValueError(
            f"flow matrix must be [..., R, R], got shape {m.shape}"
        )
    return m.reshape((-1,) + m.shape[-2:]).astype(np.int64)


def top_pairs(
    matrix: np.ndarray, k: int = 5, include_diag: bool = False
) -> List[Tuple[int, int, int]]:
    """The ``k`` hottest (src, dst, rows) links, descending by rows.

    ``include_diag=False`` (default) keeps wire links only — the
    diagonal of a ``RedistributeStats`` matrix is rows a rank kept, which
    never cross the interconnect (``MigrateStats.flow`` diagonals are
    structurally zero). Ties break toward the lower (src, dst) pair so
    the ordering is deterministic. Zero links are never reported.
    """
    m = np.asarray(matrix)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        raise ValueError(f"expected an [R, R] matrix, got shape {m.shape}")
    m = m.astype(np.int64, copy=True)
    if not include_diag:
        np.fill_diagonal(m, 0)
    flat = m.reshape(-1)
    # stable sort on (-rows, flat index): deterministic ties
    order = np.lexsort((np.arange(flat.size), -flat))
    out = []
    R = m.shape[0]
    for idx in order[: max(0, int(k))]:
        rows = int(flat[idx])
        if rows <= 0:
            break
        out.append((int(idx // R), int(idx % R), rows))
    return out


class FlowAccumulator:
    """Host-side flow gauge: cumulative matrix + per-step EMA + imbalance.

    Feed it step matrices with :meth:`update` wherever the driver already
    reads stats (one tiny host transfer — same contract as
    :func:`.recorder.record_migrate_steps`); read gauges with
    :meth:`snapshot`. ``ema_alpha`` weights the newest step; the EMA is
    seeded with the first step's matrix so early snapshots are not biased
    toward zero.
    """

    def __init__(self, n_ranks: Optional[int] = None, ema_alpha: float = 0.2):
        if not 0.0 < ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")
        self.n_ranks = None if n_ranks is None else int(n_ranks)
        self.ema_alpha = float(ema_alpha)
        self.cumulative: Optional[np.ndarray] = None  # [R, R] int64
        self.ema: Optional[np.ndarray] = None  # [R, R] float64
        self.steps = 0
        self.imbalance = 0.0  # latest max/mean population (0 = never fed)
        self.population: Optional[np.ndarray] = None  # latest [R] int64

    def _init(self, R: int) -> None:
        if self.n_ranks is None:
            self.n_ranks = R
        elif self.n_ranks != R:
            raise ValueError(
                f"flow matrix is {R}x{R} but accumulator was built for "
                f"{self.n_ranks} ranks"
            )
        if self.cumulative is None:
            self.cumulative = np.zeros((R, R), np.int64)

    def update(self, stats_or_matrix, population=None) -> None:
        """Fold one step (or a step-stacked run) into the gauges.

        Accepts a stats pytree (:func:`flow_matrix_of` applied) or a raw
        ``[R, R]`` / ``[S, R, R]`` array. ``population`` ([R] or [S, R])
        refreshes the imbalance gauge; when the argument is a
        ``MigrateStats`` its own population leaf is used automatically.
        """
        if hasattr(stats_or_matrix, "flow") or hasattr(
            stats_or_matrix, "send_counts"
        ):
            m = flow_matrix_of(stats_or_matrix)
            if population is None and hasattr(stats_or_matrix, "population"):
                population = stats_or_matrix.population
            elif population is None:
                # redistribute path: rows each rank ended the exchange
                # with (column sums, diagonal included) IS its load
                population = m.sum(axis=1)
        else:
            m = np.asarray(stats_or_matrix)
            if m.ndim == 2:
                m = m[None]
            if m.ndim != 3 or m.shape[-1] != m.shape[-2]:
                raise ValueError(
                    f"expected [R, R] or [S, R, R], got shape {m.shape}"
                )
            m = m.astype(np.int64)
        self._init(m.shape[-1])
        self.cumulative += m.sum(axis=0)
        for step in m.astype(np.float64):
            if self.ema is None:
                self.ema = step
            else:
                a = self.ema_alpha
                self.ema = a * step + (1.0 - a) * self.ema
        self.steps += m.shape[0]
        if population is not None:
            pop = np.asarray(population)
            per_rank = pop.reshape(-1, pop.shape[-1])[-1].astype(np.int64)
            total = int(per_rank.sum())
            if int(per_rank.min(initial=0)) < 0:
                raise ValueError(
                    f"population must be non-negative, got {per_rank}"
                )
            self.population = per_rank
            # total == 0 means EVERY rank is empty (counts are
            # non-negative): an empty system is perfectly balanced, so
            # the gauge reads 1.0 — the old 0.0 sentinel conflated
            # "all-empty" with "never fed", and a some-ranks-empty
            # population (total > 0) must still read max/mean, where the
            # empty ranks rightly push the ratio UP, not reset it
            self.imbalance = (
                float(int(per_rank.max()) * per_rank.size / total)
                if total > 0 else 1.0
            )

    def top_pairs(
        self, k: int = 5, ema: bool = False
    ) -> List[Tuple[int, int, int]]:
        """Hottest off-diagonal links, cumulative (default) or by EMA."""
        src = self.ema if ema else self.cumulative
        if src is None:
            return []
        return top_pairs(np.asarray(src).astype(np.int64), k=k)

    def snapshot(self, k: int = 5) -> Dict[str, object]:
        """JSON-serializable gauge snapshot (compact: no full matrix —
        ``population`` is [R] scalars, bounded by the rank count)."""
        moved = 0
        if self.cumulative is not None:
            c = self.cumulative
            moved = int(c.sum() - np.trace(c))
        return {
            "steps": int(self.steps),
            "n_ranks": self.n_ranks,
            "moved_rows_total": moved,
            "imbalance": float(self.imbalance),
            "population": (
                None if self.population is None
                else self.population.tolist()
            ),
            "top_pairs": [list(p) for p in self.top_pairs(k=k)],
        }


def record_flow_snapshot(recorder, acc: FlowAccumulator, k: int = 5) -> None:
    """Journal one compact ``flow_snapshot`` event from an accumulator.

    The payload is the :meth:`FlowAccumulator.snapshot` dict flattened to
    scalars plus a ``top_pairs`` list — small enough for the ring, rich
    enough for :mod:`.health` imbalance rules and the trace export.
    """
    recorder.record("flow_snapshot", **acc.snapshot(k=k))


def link_report(
    matrix: np.ndarray,
    row_bytes: int,
    *,
    step_seconds: Optional[float] = None,
    domain: str = "ici",
    k: int = 5,
) -> Dict[str, object]:
    """Per-link moved bytes (and bandwidth, given honest step seconds).

    ``matrix`` is one ``[R, R]`` mean-per-step flow matrix (average
    :func:`flow_matrix_of` output over the step axis for a run). Each
    off-diagonal link's bytes/step is ``rows * row_bytes``; with
    ``step_seconds`` the per-link rate is compared against ONE link's
    roof (``profiling.ICI_LINK_BYTES_PER_SEC`` for ``"ici"``, the HBM
    roof for single-chip ``"hbm"`` exchanges) — the per-link refinement
    of the aggregate ``bw_util``. Returns the ``k`` hottest links.
    """
    m = np.asarray(matrix, np.float64)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        raise ValueError(f"expected an [R, R] matrix, got shape {m.shape}")
    roof = (
        profiling.ICI_LINK_BYTES_PER_SEC
        if domain == "ici"
        else profiling.exchange_peak_bytes_per_sec(domain)
    )
    off = m.copy()
    np.fill_diagonal(off, 0.0)
    pairs = top_pairs(np.rint(off).astype(np.int64), k=k)
    links = []
    for src, dst, rows in pairs:
        byts = float(off[src, dst]) * row_bytes
        entry: Dict[str, object] = {
            "src": src,
            "dst": dst,
            "rows_per_step": float(off[src, dst]),
            "bytes_per_step": byts,
            "bytes_per_sec": None,
            "bw_util": None,
        }
        if step_seconds is not None and step_seconds > 0:
            bps = byts / step_seconds
            entry["bytes_per_sec"] = bps
            entry["bw_util"] = bps / roof
        links.append(entry)
    return {
        "domain": domain,
        "link_roof_bytes_per_sec": roof,
        "links": links,
    }
