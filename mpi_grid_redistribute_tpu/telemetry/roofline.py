"""Analytic rooflines from the XLA cost model (ISSUE 14).

The knockout tables (``telemetry/phases.py``) attribute MEASURED time;
this module supplies the other half of the attribution story: what the
program SHOULD cost. ``jax.stages.Compiled.cost_analysis()`` exposes
XLA's own per-program cost model — total FLOPs and bytes accessed —
which, divided by the chip roofs in ``utils/profiling.py`` (HBM bytes/s,
summed ICI link bytes/s, peak FLOP/s), yields a predicted step time and
a bound-by classification per program:

* ``compute``    — FLOPs / peak FLOP/s dominates;
* ``memory``     — bytes accessed / HBM peak dominates;
* ``collective`` — the J004 static collective bytes / the ICI roof
  dominates (the wire, not the local traffic).

``roofline_report()`` runs this over every progcheck-registered program
and CROSS-CHECKS the cost model against the committed static wire model
(J004 ``profiles`` + S004 ``wire_attribution`` sections of
``analysis/progprofile_baseline.json``): XLA's bytes-accessed figure
must cover at least the collective payload the jaxpr schedules — when it
does not (or when the backend has no cost model at all), the row is
journaled as a ``roofline`` event with ``discrepancy`` set, never
silently dropped. Passing measured min-of-k step seconds adds the
``achieved_fraction`` column (predicted/measured — how much of the
analytic roof the program realizes), which ``metrics.from_journal``
surfaces as the ``grid_roofline_achieved_fraction`` gauge.

Everything numeric is pure hand-math (``predict()``), unit-tested
against synthetic cost dicts; only :func:`program_cost` touches jax.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from mpi_grid_redistribute_tpu.utils import profiling

# bound-by verdicts, in predict() tie-break order
BOUND_COMPUTE = "compute"
BOUND_MEMORY = "memory"
BOUND_COLLECTIVE = "collective"
BOUND_UNKNOWN = "unknown"  # no cost model available on this backend


def extract_cost(cost_analysis) -> Optional[Dict[str, float]]:
    """Normalize a ``Compiled.cost_analysis()`` result to
    ``{"flops": float, "bytes_accessed": float}``.

    jax versions disagree about the container (a dict, or a 1-list of
    dicts) and backends disagree about coverage (a key may be absent —
    reported as 0.0, distinct from the whole model being absent, which
    returns ``None``).
    """
    if cost_analysis is None:
        return None
    if isinstance(cost_analysis, (list, tuple)):
        if not cost_analysis:
            return None
        cost_analysis = cost_analysis[0]
    if not isinstance(cost_analysis, dict):
        return None
    return {
        "flops": float(cost_analysis.get("flops", 0.0)),
        "bytes_accessed": float(cost_analysis.get("bytes accessed", 0.0)),
    }


def predict(
    cost: Optional[Dict[str, float]],
    collective_bytes: int = 0,
    *,
    peak_flops_per_sec: float = profiling.PEAK_FLOPS_PER_SEC,
    peak_bytes_per_sec: float = profiling.HBM_PEAK_BYTES_PER_SEC,
    collective_peak_bytes_per_sec: float = (
        profiling.ICI_LINK_BYTES_PER_SEC * profiling.ICI_LINKS_PER_CHIP
    ),
) -> Dict[str, object]:
    """Roofline prediction for one program (pure hand-math).

    Args:
      cost: :func:`extract_cost` output (``None`` = no cost model).
      collective_bytes: the J004 static collective byte total — billed
        against the ICI roof separately from local bytes, because the
        wire and HBM are independent resources.

    Returns a dict with ``t_compute_s`` / ``t_memory_s`` /
    ``t_collective_s``, their max ``t_predicted_s``, and the ``bound_by``
    verdict (the resource whose roof the max came from; ties break
    compute < memory < collective so a 0-cost program reads
    ``compute``-bound at 0 s rather than inventing a wall).
    """
    t_coll = float(collective_bytes) / collective_peak_bytes_per_sec
    if cost is None:
        return {
            "flops": None,
            "bytes_accessed": None,
            "t_compute_s": None,
            "t_memory_s": None,
            "t_collective_s": t_coll,
            "t_predicted_s": t_coll,
            "bound_by": BOUND_UNKNOWN,
        }
    t_comp = cost["flops"] / peak_flops_per_sec
    t_mem = cost["bytes_accessed"] / peak_bytes_per_sec
    t_pred = max(t_comp, t_mem, t_coll)
    if t_pred == t_comp:
        bound = BOUND_COMPUTE
    elif t_pred == t_mem:
        bound = BOUND_MEMORY
    else:
        bound = BOUND_COLLECTIVE
    return {
        "flops": cost["flops"],
        "bytes_accessed": cost["bytes_accessed"],
        "t_compute_s": t_comp,
        "t_memory_s": t_mem,
        "t_collective_s": t_coll,
        "t_predicted_s": t_pred,
        "bound_by": bound,
    }


def program_cost(spec) -> Optional[Dict[str, float]]:
    """Compile one progcheck :class:`~..analysis.progcheck.ProgramSpec`
    and read XLA's cost model. Returns ``None`` when the backend
    provides no cost analysis (degraded, not fatal — the report marks
    the row ``bound_by="unknown"`` and journals the discrepancy)."""
    import jax

    fn, args = spec.build()
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        return extract_cost(compiled.cost_analysis())
    except Exception:
        return None


def cross_check(
    cost: Optional[Dict[str, float]],
    static_profile: Optional[dict],
    wire: Optional[dict],
) -> Dict[str, object]:
    """Cost-model vs static-wire-model consistency verdict for one
    program.

    The jaxpr-derived J004 collective byte total is a LOWER bound on
    real memory traffic (every wired byte is read and written at least
    once), so ``bytes_accessed < collective_bytes_total`` means one of
    the two models is wrong — as does a missing cost model. Either way
    the caller journals it; nothing is silently dropped.
    """
    static_bytes = None
    ici_bytes = None
    if static_profile is not None:
        static_bytes = int(static_profile.get("collective_bytes_total", 0))
    if wire is not None:
        ici_bytes = int(wire.get("per_domain", {}).get("ici", 0))
    if cost is None:
        return {
            "static_collective_bytes": static_bytes,
            "static_ici_bytes": ici_bytes,
            "bytes_ratio": None,
            "discrepancy": True,
            "discrepancy_reason": "no cost model on this backend",
        }
    if static_bytes is None:
        return {
            "static_collective_bytes": None,
            "static_ici_bytes": ici_bytes,
            "bytes_ratio": None,
            "discrepancy": True,
            "discrepancy_reason": "program missing from the J004 baseline"
            " — run scripts/progcheck.py --update-baseline",
        }
    ratio = (
        cost["bytes_accessed"] / static_bytes if static_bytes > 0 else None
    )
    if static_bytes > 0 and cost["bytes_accessed"] < static_bytes:
        return {
            "static_collective_bytes": static_bytes,
            "static_ici_bytes": ici_bytes,
            "bytes_ratio": ratio,
            "discrepancy": True,
            "discrepancy_reason": (
                "cost-model bytes accessed "
                f"({cost['bytes_accessed']:.0f}) below the static "
                f"collective total ({static_bytes}) — one model is wrong"
            ),
        }
    return {
        "static_collective_bytes": static_bytes,
        "static_ici_bytes": ici_bytes,
        "bytes_ratio": ratio,
        "discrepancy": False,
        "discrepancy_reason": "",
    }


def roofline_report(
    programs: Optional[dict] = None,
    measured_s: Optional[Dict[str, float]] = None,
    recorder=None,
    *,
    peak_flops_per_sec: float = profiling.PEAK_FLOPS_PER_SEC,
    peak_bytes_per_sec: float = profiling.HBM_PEAK_BYTES_PER_SEC,
) -> Dict[str, dict]:
    """Predicted-vs-achieved roofline rows for every registered program.

    Args:
      programs: progcheck registry subset (default: all 13 registered
        programs via ``analysis.progcheck.default_programs()``).
      measured_s: optional ``{program: min-of-k step seconds}`` — fills
        ``measured_s`` and ``achieved_fraction`` (predicted/measured).
      recorder: optional ``StepRecorder`` — every row is journaled as a
        ``roofline`` event (discrepant rows included, per SCHEMA.md).

    Returns ``{program: row}`` where each row merges :func:`predict`
    and :func:`cross_check` outputs plus the achieved columns.
    """
    from mpi_grid_redistribute_tpu.analysis import progcheck
    from mpi_grid_redistribute_tpu.analysis.baseline import (
        load_progprofile_baseline,
        load_wire_baseline,
    )

    programs = progcheck.default_programs() if programs is None else programs
    measured_s = measured_s or {}
    static = load_progprofile_baseline() or {}
    wires = load_wire_baseline() or {}
    report: Dict[str, dict] = {}
    for name in sorted(programs):
        cost = program_cost(programs[name])
        prof = static.get(name)
        coll = int(prof.get("collective_bytes_total", 0)) if prof else 0
        row = predict(
            cost,
            coll,
            peak_flops_per_sec=peak_flops_per_sec,
            peak_bytes_per_sec=peak_bytes_per_sec,
        )
        row.update(cross_check(cost, prof, wires.get(name)))
        meas = measured_s.get(name)
        row["measured_s"] = meas
        row["achieved_fraction"] = (
            None
            if meas is None or not row["t_predicted_s"] or meas <= 0
            else row["t_predicted_s"] / meas
        )
        report[name] = row
        if recorder is not None:
            recorder.record(
                "roofline",
                program=name,
                phase="total",
                flops=row["flops"],
                bytes_accessed=row["bytes_accessed"],
                t_predicted_s=row["t_predicted_s"],
                bound_by=row["bound_by"],
                static_collective_bytes=row["static_collective_bytes"],
                bytes_ratio=row["bytes_ratio"],
                discrepancy=row["discrepancy"],
                discrepancy_reason=row["discrepancy_reason"],
                measured_s=meas,
                achieved_fraction=row["achieved_fraction"],
            )
    return report


def format_roofline_table(report: Dict[str, dict]) -> str:
    """Markdown roofline table (one row per program)."""
    lines = [
        "| program | flops | bytes | pred ms | bound by | achieved | "
        "xcheck |",
        "|---|---|---|---|---|---|---|",
    ]

    def _num(v, scale=1.0, fmt="{:.2f}"):
        return "—" if v is None else fmt.format(v * scale)

    for name in sorted(report):
        r = report[name]
        xc = "DISCREPANT" if r["discrepancy"] else "ok"
        lines.append(
            f"| {name} | {_num(r['flops'], 1e-6)}M "
            f"| {_num(r['bytes_accessed'], 1e-6)}MB "
            f"| {_num(r['t_predicted_s'], 1e3, '{:.4f}')} "
            f"| {r['bound_by']} "
            f"| {_num(r['achieved_fraction'], 100.0)}% "
            f"| {xc} |"
        )
    return "\n".join(lines)
