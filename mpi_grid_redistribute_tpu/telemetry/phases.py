"""Phase attribution: the knockout technique as a reusable API.

``scripts/knockout_stages.py`` established the repo's attribution method:
compile the step truncated after each phase, time each truncation with
scan-length differencing (:func:`..utils.profiling.scan_time_per_step` —
compile/dispatch/tunnel costs cancel), and read per-phase cost off the
deltas, optionally against a logical-bytes roofline. That script remains
the maintained copy of the migrate step; THIS module owns the harness, so
any loop builder — knockout copies, ablation variants, user pipelines —
gets the same protocol and the same table without re-deriving it.

Two labeling helpers complete the picture for trace-based profiling:

* :func:`span` — host-side ``jax.profiler.TraceAnnotation`` wrapper: wrap
  dispatch regions so Perfetto/XProf timelines carry the caller's names.
* :func:`traced_span` — ``jax.named_scope`` wrapper for code INSIDE jit:
  attaches the name to the XLA ops it encloses (TraceAnnotation cannot
  reach into a compiled program). The exchange/migrate engines use it on
  their bin/pack/exchange/unpack phases.
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Sequence

import jax

from mpi_grid_redistribute_tpu.utils import profiling


def span(name: str):
    """Host-side profiler span: ``with span('exchange'): out = fn(x)``.

    Labels the DISPATCH of the enclosed region in a ``jax.profiler.trace``
    capture. For labels on the device ops themselves use
    :func:`traced_span` inside the traced function."""
    return jax.profiler.TraceAnnotation(name)


def traced_span(name: str):
    """Traced-code span: ``with traced_span('rd:bin'): dest = ...``.

    A ``jax.named_scope`` — the name lands in XLA op metadata, so
    Perfetto/XProf group the enclosed ops under it. Safe inside jit,
    scan bodies and shard_map (purely metadata; no ops inserted).
    """
    return jax.named_scope(name)


class PhaseTiming(NamedTuple):
    """One row of an attribution run. ``cumulative_s`` is the truncated
    step's per-step time; ``delta_s`` the increment over the previous
    phase (the phase's attributed cost); roofline fields are populated
    when logical bytes were supplied."""

    phase: object
    cumulative_s: float
    delta_s: float
    logical_bytes: Optional[int] = None
    roofline_s: Optional[float] = None

    @property
    def x_roofline(self) -> Optional[float]:
        """measured delta / roofline time; >>1 flags latency/serialization
        bound (scatters, sorts), not a bandwidth wall."""
        if not self.roofline_s or self.roofline_s <= 0:
            return None
        return self.delta_s / self.roofline_s


def attribute_phases(
    loop_builder: Callable[[object, int], Callable],
    args,
    phases: Sequence,
    *,
    s1: int = 4,
    s2: int = 16,
    reps: int = 2,
    phase_bytes: Optional[dict] = None,
    peak_bytes_per_sec: float = profiling.HBM_PEAK_BYTES_PER_SEC,
    progress: Optional[Callable[[PhaseTiming], None]] = None,
) -> List[PhaseTiming]:
    """Attribute a step's time to its phases by cumulative truncation.

    Args:
      loop_builder: ``loop_builder(phase, S)`` must return a jitted
        callable running ``S`` steps of the pipeline truncated after
        ``phase`` (phases are caller-defined tokens — ints, names).
        Each truncation must keep a data dependency on its last phase's
        output so XLA cannot dead-code-eliminate the work (see
        ``scripts/knockout_stages.py`` ``dep_out`` for the idiom).
      args: loop inputs, passed through to the built loops.
      phases: ordered phase tokens; deltas attribute ``phases[i]``'s cost
        as ``cumulative[i] - cumulative[i-1]`` (the first row's delta is
        its cumulative time — everything up to and including it).
      s1/s2/reps: scan-differencing protocol knobs
        (:func:`..utils.profiling.scan_time_per_step`).
      phase_bytes: optional ``{phase: logical_bytes}`` — minimum traffic
        each phase's math implies; fills the roofline columns.
      peak_bytes_per_sec: roofline denominator (defaults to the v5e HBM
        peak; pass an ICI roof for wire-bound phases).
      progress: optional callback invoked with each finished row (the
        knockout script streams its table through this).

    Returns one :class:`PhaseTiming` per phase, in order.
    """
    out: List[PhaseTiming] = []
    prev = None
    for phase in phases:
        per_step, _overhead, _last = profiling.scan_time_per_step(
            lambda S, phase=phase: loop_builder(phase, S),
            args, s1=s1, s2=s2, reps=reps,
        )
        del _last  # GB-scale output pytrees must not pile up across phases
        delta = per_step if prev is None else per_step - prev
        lb = None if phase_bytes is None else phase_bytes.get(phase)
        roof = None if lb is None else lb / peak_bytes_per_sec
        row = PhaseTiming(phase, per_step, delta, lb, roof)
        out.append(row)
        if progress is not None:
            progress(row)
        prev = per_step
    return out


def format_phase_table(timings: Sequence[PhaseTiming]) -> str:
    """Markdown knockout table (the BENCH_CONFIGS.md format): cumulative
    ms, delta ms, logical MB, roofline ms, x-roofline."""
    lines = [
        "| phase (cumulative) | ms | delta | logical MB | roofline ms "
        "| x-roofline |",
        "|---|---|---|---|---|---|",
    ]
    for i, t in enumerate(timings):
        mb = "—" if t.logical_bytes is None else f"{t.logical_bytes/1e6:8.1f}"
        roof = "—" if t.roofline_s is None else f"{t.roofline_s*1e3:6.2f}"
        xr = t.x_roofline
        xcol = "—" if xr is None else f"{xr:6.1f}"
        delta = "(first)" if i == 0 else f"{t.delta_s*1e3:+7.2f}"
        lines.append(
            f"| {t.phase} | {t.cumulative_s*1e3:7.2f} | {delta} | {mb} "
            f"| {roof} | {xcol} |"
        )
    return "\n".join(lines)
