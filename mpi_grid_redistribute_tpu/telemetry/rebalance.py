"""Closed-loop adaptive rebalancing: plan + amortization guard.

The observability plane (flow gauges, health rules) can *see* a stale
decomposition — under a drifting hot spot the ``imbalance_ratio`` rule
fires step after step while one rank drowns — but until this module it
could only page an operator. This is the planning half of the actuation
loop (ROADMAP item 2):

* :class:`RebalancePlanner` measures the live per-cell occupancy
  histogram over a FINE uniform cell grid (``cells_per_rank_axis`` fine
  cells per grid cell per axis, binned with the exact
  ``ops.binning`` digitize the engines route by, so the plan and the
  actuation cannot disagree), feeds it to the existing LPT machinery
  (``parallel.migrate.balanced_assignment``), and emits assignment-aware
  :class:`~..domain.GridEdges` — the fresh cell -> vrank map.
* :class:`AmortizationGuard` decides whether applying the plan pays:
  the one-shot "big redistribute" costs real time (recompile + a
  near-total row permutation), so it only fires when the projected
  per-step saving clears the measured apply cost within a configurable
  horizon, with a cooldown so back-to-back remaps can never thrash.

The actuation itself is ``GridRedistribute.apply_assignment`` (one
canonical redistribute under the new edges); the wiring — ALERT ->
plan -> guard -> apply, with a journaled ``rebalance`` event either way
(telemetry/SCHEMA.md) — lives in ``service.driver``.

Projected-saving model (deliberately first-order): the drift loop's step
time is dominated by the hottest rank — padded shapes, capacity growth
and the exchange all key off the max-loaded shard — so per-step time
scales ~ with the imbalance ratio (max/mean), and a remap from
``old_imb`` to ``proj_imb`` projects a per-step saving of
``step_seconds * (1 - proj_imb / old_imb)``. Crude, but it is compared
against a MEASURED cost (EMA of realized apply times, seeded by a
configurable multiple of the step time before the first apply), and the
journal records projected vs realized so the model's honesty is
auditable.

Host-side only: planning is NumPy over host state the driver already
holds; nothing here syncs the device.
"""
# gridlint: service-path

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from mpi_grid_redistribute_tpu.domain import Domain, GridEdges, ProcessGrid


class RebalancePlan(NamedTuple):
    """One planner output: the fresh map plus the numbers the guard and
    the ``rebalance`` journal event need."""

    edges: GridEdges            # assignment-aware fine-cell -> rank map
    old_imbalance: float        # max/mean of the measured population
    projected_imbalance: float  # max/mean of the LPT bin loads
    n_cells: int                # fine cells in the plan
    occupied_cells: int         # fine cells with nonzero load


class RebalancePlanner:
    """Measure occupancy, run LPT, emit assignment-aware edges.

    ``cells_per_rank_axis`` sets the planning granularity: each grid
    cell is split into that many fine cells per axis, so an 8-rank
    ``(2, 2, 2)`` grid at factor 2 plans over 64 fine cells (8 per
    rank) — enough freedom for LPT to split a hot spot across ranks
    while keeping the assignment table a small jit-time constant.
    """

    def __init__(
        self,
        domain: Domain,
        grid: ProcessGrid,
        cells_per_rank_axis: int = 2,
    ):
        if int(cells_per_rank_axis) < 1:
            raise ValueError(
                f"cells_per_rank_axis must be >= 1, got {cells_per_rank_axis}"
            )
        grid.validate_against(domain)
        self.domain = domain
        self.grid = grid
        self.cells_shape = tuple(
            s * int(cells_per_rank_axis) for s in grid.shape
        )
        # uniform fine edges, endpoints exact (np.linspace pins both)
        self.fine_edges = tuple(
            tuple(
                float(v)
                for v in np.linspace(
                    domain.lo[a], domain.hi[a], self.cells_shape[a] + 1
                )
            )
            for a in range(grid.ndim)
        )

    def _live_rows(self, positions, count) -> np.ndarray:
        pos = np.asarray(positions)
        R = self.grid.nranks
        if pos.ndim != 2 or pos.shape[0] % R:
            raise ValueError(
                f"positions must be [R*n_local, ndim] over {R} ranks, "
                f"got {pos.shape}"
            )
        if count is None:
            return pos
        n_local = pos.shape[0] // R
        c = np.asarray(count, dtype=np.int64)
        mask = np.arange(n_local)[None, :] < c[:, None]
        return pos.reshape(R, n_local, -1)[mask]

    def occupancy(self, positions, count=None) -> np.ndarray:
        """Per-fine-cell live-row histogram ([n_cells] int64, row-major)
        from the padded global layout — the SAME wrap + digitize the
        engines route by (``ops.binning`` with ``xp=np``), so a cell's
        measured load is exactly the rows the actuation will land there.
        """
        from mpi_grid_redistribute_tpu.ops import binning

        live = self._live_rows(positions, count)
        probe = GridEdges(self.fine_edges)
        wrapped = binning.wrap_periodic(
            live.astype(np.float32, copy=False), self.domain, xp=np
        )
        cell = binning.cell_of_position(
            wrapped, self.domain, self.grid, xp=np, edges=probe
        )
        strides = probe.cell_strides
        flat = (cell.astype(np.int64) * np.asarray(strides)).sum(axis=-1)
        return np.bincount(
            flat, minlength=int(np.prod(self.cells_shape))
        ).astype(np.int64)

    def plan(self, positions, count=None) -> Optional[RebalancePlan]:
        """One fresh cell -> rank map from the current state, or ``None``
        when there is nothing to balance (no live rows)."""
        from mpi_grid_redistribute_tpu.parallel import migrate

        loads = self.occupancy(positions, count)
        total = int(loads.sum())
        if total == 0:
            return None
        R = self.grid.nranks
        assignment = migrate.balanced_assignment(loads, R)
        bins = np.bincount(
            np.asarray(assignment), weights=loads.astype(np.float64),
            minlength=R,
        )
        projected = float(bins.max() / bins.mean())
        if count is None:
            old = 1.0
        else:
            c = np.asarray(count, dtype=np.float64)
            old = float(c.max() / c.mean()) if c.mean() > 0 else 1.0
        return RebalancePlan(
            edges=GridEdges(self.fine_edges, assignment),
            old_imbalance=old,
            projected_imbalance=projected,
            n_cells=int(loads.size),
            occupied_cells=int((loads > 0).sum()),
        )


class GuardDecision(NamedTuple):
    """One :meth:`AmortizationGuard.consider` verdict — everything the
    ``rebalance`` journal event needs to explain itself."""

    apply: bool
    reason: str                  # human decision trail (skip reason or "go")
    projected_saving_s: float    # projected per-step saving (seconds)
    cost_s: float                # apply cost the decision compared against


class AmortizationGuard:
    """Fire the big redistribute only when it amortizes.

    The decision inputs are gauges the driver already has (step-time
    EMA, the planner's old/projected imbalance); the cost side starts as
    ``initial_cost_factor`` x the step time (a remap is a near-total
    permutation plus a recompile, reliably several steps' worth) and
    converges to the EMA of MEASURED apply costs after the first apply.
    ``cooldown_steps`` enforces hysteresis: however loud the gauges, two
    remaps can never run closer than the cooldown, so a plan/actuate
    feedback oscillation cannot thrash.
    """

    def __init__(
        self,
        horizon_steps: int = 256,
        cooldown_steps: int = 64,
        min_improvement: float = 0.05,
        initial_cost_factor: float = 8.0,
        cost_alpha: float = 0.5,
    ):
        if int(horizon_steps) < 1:
            raise ValueError(
                f"horizon_steps must be >= 1, got {horizon_steps}"
            )
        if int(cooldown_steps) < 0:
            raise ValueError(
                f"cooldown_steps must be >= 0, got {cooldown_steps}"
            )
        if not 0.0 <= float(min_improvement) < 1.0:
            raise ValueError(
                f"min_improvement must be in [0, 1), got {min_improvement}"
            )
        if not 0.0 < float(cost_alpha) <= 1.0:
            raise ValueError(
                f"cost_alpha must be in (0, 1], got {cost_alpha}"
            )
        self.horizon_steps = int(horizon_steps)
        self.cooldown_steps = int(cooldown_steps)
        self.min_improvement = float(min_improvement)
        self.initial_cost_factor = float(initial_cost_factor)
        self.cost_alpha = float(cost_alpha)
        self.cost_ema_s: Optional[float] = None  # measured apply cost
        self.last_applied_step: Optional[int] = None
        self.applies = 0

    def consider(
        self,
        *,
        step: int,
        step_seconds: float,
        old_imbalance: float,
        projected_imbalance: float,
    ) -> GuardDecision:
        """Should the plan be applied now? Pure decision — no state
        changes (call :meth:`note_applied` after a realized apply)."""
        cost = (
            self.cost_ema_s
            if self.cost_ema_s is not None
            else self.initial_cost_factor * max(0.0, float(step_seconds))
        )
        if (
            self.last_applied_step is not None
            and step - self.last_applied_step < self.cooldown_steps
        ):
            remaining = self.cooldown_steps - (step - self.last_applied_step)
            return GuardDecision(
                False,
                f"cooldown: last rebalance at step "
                f"{self.last_applied_step}, {remaining} steps remaining",
                0.0,
                cost,
            )
        if old_imbalance <= 0.0:
            return GuardDecision(
                False, "no measured imbalance to improve on", 0.0, cost
            )
        improvement = 1.0 - projected_imbalance / old_imbalance
        saving = max(0.0, float(step_seconds)) * improvement
        if improvement < self.min_improvement:
            return GuardDecision(
                False,
                f"projected improvement {improvement:.1%} below the "
                f"{self.min_improvement:.1%} floor "
                f"({old_imbalance:.2f}x -> {projected_imbalance:.2f}x)",
                max(0.0, saving),
                cost,
            )
        horizon_saving = saving * self.horizon_steps
        if horizon_saving <= cost:
            return GuardDecision(
                False,
                f"projected saving {saving * 1e3:.3f} ms/step x "
                f"{self.horizon_steps} steps = {horizon_saving * 1e3:.1f} "
                f"ms does not clear the {cost * 1e3:.1f} ms apply cost",
                saving,
                cost,
            )
        return GuardDecision(
            True,
            f"projected saving {saving * 1e3:.3f} ms/step clears the "
            f"{cost * 1e3:.1f} ms apply cost within {self.horizon_steps} "
            f"steps ({old_imbalance:.2f}x -> {projected_imbalance:.2f}x)",
            saving,
            cost,
        )

    def note_applied(self, step: int, cost_seconds: float) -> None:
        """Fold one realized apply: arms the cooldown and replaces the
        seeded cost estimate with a measured EMA."""
        self.last_applied_step = int(step)
        self.applies += 1
        c = max(0.0, float(cost_seconds))
        self.cost_ema_s = (
            c
            if self.cost_ema_s is None
            else self.cost_alpha * c
            + (1.0 - self.cost_alpha) * self.cost_ema_s
        )
