"""Metrics plane: Counter/Gauge/Histogram registry + OpenMetrics text.

The journal (:mod:`.recorder`) is the repo's source of truth for what
happened; this module is the *scrapable* projection of it — the surface
a production pod job exposes to Prometheus-compatible collectors
(ROADMAP north star: long-running heavy-traffic serving, not post-hoc
single-process analysis).

Two ways to populate a :class:`MetricsRegistry`:

* direct instrumentation — ``reg.counter(...)`` / ``.gauge(...)`` /
  ``.histogram(...)`` hand out families; children are addressed by
  label values and mutated with ``inc``/``set``/``observe``;
* journal replay — :func:`from_journal` folds a ``StepRecorder`` (or an
  exported/merged JSONL event stream) into the standard grid metric
  families. The ``grid_journal_events_total`` family is built from the
  recorder's *all-time* counts, so scrape totals are exact even after
  ring eviction.

:func:`render_openmetrics` emits the OpenMetrics text exposition format
(``# TYPE``/``# HELP`` metadata, ``_total`` counter samples, cumulative
``_bucket{le=...}`` histograms, terminating ``# EOF``) — the format
``scripts/metrics_serve.py`` serves on ``/metrics``.

Scrape-path purity: this module is host-only and must not import jax
(directly or transitively) — a scrape must never trigger device work or
a blocking device read. ``tests/test_metrics.py`` enforces this and the
no-device-read contract is the same G002 invariant gridlint checks on
the jit path.
"""

from __future__ import annotations

# gridlint: scrape-path

import math
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# OpenMetrics reserves the _total/_bucket/_sum/_count suffixes for the
# renderer to append; family base names must not collide with them.
_RESERVED_SUFFIXES = ("_total", "_bucket", "_sum", "_count", "_created")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric/label name: {name!r}")
    for suf in _RESERVED_SUFFIXES:
        if name.endswith(suf):
            raise ValueError(
                f"metric name {name!r} ends with reserved suffix {suf!r}"
                " (the OpenMetrics renderer appends it)"
            )
    return name


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(v) -> str:
    """Shortest round-trip text for a sample value (repr for floats —
    exact; plain int for integral counters)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def pow2_edges(lo: int, hi: int) -> Tuple[float, ...]:
    """Histogram bucket edges at powers of two: ``2**lo .. 2**hi``
    inclusive. The grid's quantities span decades (step times from µs
    spin-ups to multi-second stalls, mover counts from 1 to millions);
    pow2 buckets give constant relative resolution with a handful of
    buckets and exactly representable edges."""
    if hi < lo:
        raise ValueError(f"pow2_edges: hi {hi} < lo {lo}")
    return tuple(float(2.0 ** e) for e in range(int(lo), int(hi) + 1))


class _Child:
    __slots__ = ("_labels",)

    def __init__(self, labels: Tuple[str, ...]):
        self._labels = labels


class Counter(_Child):
    """Monotone non-negative count. ``inc`` by a non-negative amount."""

    __slots__ = ("_value",)

    def __init__(self, labels: Tuple[str, ...]):
        super().__init__(labels)
        self._value = 0

    def inc(self, amount: float = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter decrease: {amount}")
        self._value += amount

    @property
    def value(self):
        return self._value


class Gauge(_Child):
    """Point-in-time value; may go up or down."""

    __slots__ = ("_value",)

    def __init__(self, labels: Tuple[str, ...]):
        super().__init__(labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = value

    def inc(self, amount: float = 1) -> None:
        self._value += amount

    def dec(self, amount: float = 1) -> None:
        self._value -= amount

    @property
    def value(self):
        return self._value


class Histogram(_Child):
    """Distribution over fixed edges; per-bucket counts are stored
    non-cumulative and rendered cumulative (OpenMetrics ``le`` buckets
    include an implicit ``+Inf``)."""

    __slots__ = ("_edges", "_bucket_counts", "_sum", "_count")

    def __init__(self, labels: Tuple[str, ...], edges: Sequence[float]):
        super().__init__(labels)
        self._edges = tuple(float(e) for e in edges)
        # one slot per finite edge plus the +Inf overflow slot
        self._bucket_counts = [0] * (len(self._edges) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self._sum += v
        self._count += 1
        for i, edge in enumerate(self._edges):
            if v <= edge:
                self._bucket_counts[i] += 1
                return
        self._bucket_counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """``(le, cumulative_count)`` pairs, ending with ``(+Inf, count)``."""
        out, acc = [], 0
        for edge, n in zip(self._edges, self._bucket_counts):
            acc += n
            out.append((edge, acc))
        out.append((math.inf, self._count))
        return out

    def quantile(self, q: float) -> float:
        """Bucketed upper-bound ``q``-quantile: the smallest edge whose
        cumulative count covers ``ceil(q * count)`` observations.

        This is the estimate a Prometheus ``histogram_quantile`` over
        the rendered buckets would bound, so an SLO rule computed here
        (health.py ``slo_latency_p99``) agrees with what an operator
        sees on ``/metrics``. Returns ``+Inf`` when the quantile lands
        in the overflow bucket and ``0.0`` on an empty histogram.
        """
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile q must be in (0, 1], got {q}")
        if self._count == 0:
            return 0.0
        target = max(1, math.ceil(q * self._count))
        acc = 0
        for edge, n in zip(self._edges, self._bucket_counts):
            acc += n
            if acc >= target:
                return edge
        return math.inf


_CHILD_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric family: a type, help text, a fixed label-name
    tuple, and one child per distinct label-value tuple."""

    def __init__(
        self,
        name: str,
        mtype: str,
        help: str,
        labelnames: Sequence[str] = (),
        edges: Optional[Sequence[float]] = None,
    ):
        if mtype not in _CHILD_TYPES:
            raise ValueError(f"unknown metric type: {mtype!r}")
        self.name = _check_name(name)
        self.mtype = mtype
        self.help = str(help)
        self.labelnames = tuple(_check_name(ln) for ln in labelnames)
        if mtype == "histogram":
            if not edges:
                raise ValueError(f"histogram {name!r} needs bucket edges")
            es = [float(e) for e in edges]
            if any(b <= a for a, b in zip(es, es[1:])):
                raise ValueError(
                    f"histogram {name!r} edges must strictly increase"
                )
            self.edges: Optional[Tuple[float, ...]] = tuple(es)
        else:
            if edges is not None:
                raise ValueError(f"{mtype} {name!r} takes no edges")
            self.edges = None
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def labels(self, **kv) -> _Child:
        """The child for these label values (created on first use)."""
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(kv)}, "
                f"declared {sorted(self.labelnames)}"
            )
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is None:
            if self.mtype == "histogram":
                child = Histogram(key, self.edges)
            else:
                child = _CHILD_TYPES[self.mtype](key)
            self._children[key] = child
        return child

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        return list(self._children.items())

    def _label_str(self, values: Tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{ln}="{_escape_label(v)}"'
            for ln, v in zip(self.labelnames, values)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class MetricsRegistry:
    """An ordered set of metric families with one rendering.

    Family accessors are idempotent: re-declaring an existing name with
    the same type/labels returns the existing family (so journal replay
    and direct instrumentation can share a registry); re-declaring with
    a different shape raises.
    """

    def __init__(self):
        self._families: Dict[str, Family] = {}

    def _family(self, name, mtype, help, labelnames, edges=None) -> Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.mtype != mtype or fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} re-declared with different "
                    f"type/labels ({fam.mtype}{fam.labelnames} vs "
                    f"{mtype}{tuple(labelnames)})"
                )
            if mtype == "histogram" and fam.edges != tuple(
                float(e) for e in edges
            ):
                raise ValueError(
                    f"histogram {name!r} re-declared with different edges"
                )
            return fam
        fam = Family(name, mtype, help, labelnames, edges)
        self._families[name] = fam
        return fam

    def counter(self, name, help, labelnames=()) -> Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name, help, labelnames=()) -> Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name, help, labelnames=(), edges=()) -> Family:
        return self._family(name, "histogram", help, labelnames, edges)

    def families(self) -> List[Family]:
        return list(self._families.values())

    def get(self, name: str) -> Optional[Family]:
        return self._families.get(name)

    def render_openmetrics(self) -> str:
        return render_openmetrics(self)

    @classmethod
    def from_journal(cls, source, **kw) -> "MetricsRegistry":
        return from_journal(source, registry=cls(), **kw)


def render_openmetrics(registry: MetricsRegistry) -> str:
    """OpenMetrics text exposition of every family in the registry.

    Counters render as ``<name>_total``; histograms as cumulative
    ``<name>_bucket{le="..."}`` plus ``_sum``/``_count`` with a final
    ``le="+Inf"`` bucket equal to ``_count``; the document terminates
    with ``# EOF``. Label values are escaped per the spec
    (backslash, quote, newline). ``tests/test_metrics.py`` parses this
    back with a strict hand parser."""
    lines: List[str] = []
    for fam in registry.families():
        lines.append(f"# TYPE {fam.name} {fam.mtype}")
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_label(fam.help)}")
        for values, child in fam.children():
            if fam.mtype == "counter":
                lines.append(
                    f"{fam.name}_total{fam._label_str(values)}"
                    f" {_format_value(child.value)}"
                )
            elif fam.mtype == "gauge":
                lines.append(
                    f"{fam.name}{fam._label_str(values)}"
                    f" {_format_value(child.value)}"
                )
            else:
                for le, acc in child.cumulative():
                    le_txt = "+Inf" if math.isinf(le) else _format_value(le)
                    label_str = fam._label_str(
                        values, 'le="%s"' % le_txt
                    )
                    lines.append(f"{fam.name}_bucket{label_str} {acc}")
                lines.append(
                    f"{fam.name}_sum{fam._label_str(values)}"
                    f" {_format_value(child.sum)}"
                )
                lines.append(
                    f"{fam.name}_count{fam._label_str(values)}"
                    f" {child.count}"
                )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Journal replay: fold recorded events into the standard grid families.

# step times: 2^-14 s (~61 µs) .. 2^4 s (16 s)
STEP_TIME_EDGES = pow2_edges(-14, 4)
# mover counts: 1 .. 2^24 (~16.7M rows/step)
MOVERS_EDGES = pow2_edges(0, 24)
# dropped rows per step: an explicit 0 bucket (loss-free steps must be
# distinguishable from <=1-row loss, and the p99-of-zeros must be 0 for
# the threshold=0 SLO), then 1 .. 2^24 (same span as movers)
DROPPED_EDGES = (0.0,) + pow2_edges(0, 24)


def _iter_events(source) -> Tuple[Iterable[tuple], Optional[Dict[str, int]]]:
    """Normalize a journal source to ``(events, all_time_counts)``.

    ``events`` yields ``(kind, data)`` pairs; ``all_time_counts`` is the
    exact per-kind total when the source knows it (a ``StepRecorder`` or
    a merged journal), else None (counted from the stream)."""
    counts = None
    if hasattr(source, "events") and hasattr(source, "counts"):
        # StepRecorder or aggregate.MergedJournal
        counts = dict(source.counts())
        events = []
        for e in source.events():
            if hasattr(e, "kind"):
                events.append((e.kind, dict(e.data)))
            else:  # merged journal dict rows
                d = dict(e)
                kind = d.pop("kind")
                for env in ("seq", "time", "host", "pid", "t_aligned"):
                    d.pop(env, None)
                events.append((kind, d))
        return events, counts
    # iterable of JSONL-decoded dicts
    events = []
    for row in source:
        d = dict(row)
        kind = d.pop("kind")
        for env in ("seq", "time", "host", "pid", "t_aligned"):
            d.pop(env, None)
        events.append((kind, d))
    return events, None


def from_journal(
    source,
    registry: Optional[MetricsRegistry] = None,
    prefix: str = "grid",
) -> MetricsRegistry:
    """Fold a journal into the standard grid metric families.

    ``source`` is a ``StepRecorder``, an ``aggregate.MergedJournal``, or
    any iterable of JSONL-decoded event dicts. When the source carries
    all-time counts, ``<prefix>_journal_events_total`` uses them — exact
    even after ring eviction — and ``<prefix>_journal_evicted_events``
    reports how many retained-window-only samples the other families are
    missing.

    Families (documented in SCHEMA.md "Metric families"):

    * ``journal_events_total{kind}`` — all-time events per kind;
    * ``migrate_rows_total{direction}`` — sent/received/backlog/
      dropped_recv row totals over the journaled ``migrate_step`` window;
    * ``population_rows`` / ``backlog_rows`` — latest step gauges;
    * ``step_time_seconds`` — pow2 histogram of ``step_time`` samples;
    * ``fast_path_steps_total{taken}`` + ``movers_per_step`` histogram;
    * ``capacity_rows{which}`` — latest ratcheted capacity per budget;
    * ``exchange_wire_bytes_total{engine}`` — scheduled canonical-
      exchange wire bytes per engine over the journaled
      ``redistribute`` window;
    * ``alerts_total{rule,severity}`` — health findings journaled;
    * ``flow_moved_rows`` / ``flow_imbalance`` /
      ``rank_population{vrank}`` — latest flow snapshot;
    * ``step_latency_seconds`` / ``dropped_rows`` — pow2 histograms of
      the service driver's ``step_latency`` events (the SLO surface);
    * ``snapshot_corrupt_total`` — corrupt snapshots skipped at restore;
    * ``roofline_achieved_fraction{program,phase}`` — latest analytic
      predicted/measured fraction per ``roofline`` event;
    * ``profile_sessions_total`` — ``profile_session`` events (profiler
      captures attempted);
    * ``state_nan_total{field}`` / ``state_oob_total`` — corrupt-row
      totals over the journaled ``state_health`` window (ISSUE 20);
    * ``state_live_rows`` / ``state_residual`` — latest conservation-
      ledger gauges (a nonzero residual is row loss/creation the
      exchange never accounted).
    """
    reg = registry if registry is not None else MetricsRegistry()
    events, counts = _iter_events(source)
    p = prefix

    ev_total = reg.counter(
        f"{p}_journal_events",
        "All-time journal events per kind (survives ring eviction)",
        ("kind",),
    )
    if counts is None:
        counts = {}
        for kind, _ in events:
            counts[kind] = counts.get(kind, 0) + 1
    for kind in sorted(counts):
        ev_total.labels(kind=kind).inc(counts[kind])
    evicted = reg.gauge(
        f"{p}_journal_evicted_events",
        "Events recorded but no longer retained (ring wrapped); the"
        " non-counter families below cover the retained window only",
    )
    total_events = sum(counts.values())
    evicted.labels().set(max(0, total_events - len(events)))

    rows = reg.counter(
        f"{p}_migrate_rows",
        "Rows by direction over the journaled migrate_step window",
        ("direction",),
    )
    pop_g = reg.gauge(
        f"{p}_population_rows", "Total resident rows at the latest step"
    )
    back_g = reg.gauge(
        f"{p}_backlog_rows", "Deferred (capacity-limited) rows, latest step"
    )
    st_h = reg.histogram(
        f"{p}_step_time_seconds",
        "Measured wall step times (pow2 buckets)",
        edges=STEP_TIME_EDGES,
    )
    lat_h = reg.histogram(
        f"{p}_step_latency_seconds",
        "Service-driver end-to-end step latency (step_latency events,"
        " pow2 buckets) — the SLO surface the restart policy actuates on",
        edges=STEP_TIME_EDGES,
    )
    drop_h = reg.histogram(
        f"{p}_dropped_rows",
        "Rows dropped per service step (step_latency events, pow2"
        " buckets); any nonzero sample is row loss",
        edges=DROPPED_EDGES,
    )
    corrupt_c = reg.counter(
        f"{p}_snapshot_corrupt",
        "Corrupt snapshots skipped over during restores (restore"
        " events' snapshots_skipped)",
    )
    fp_total = reg.counter(
        f"{p}_fast_path_steps",
        "Sparse-engine routing outcomes (taken=1 sparse, 0 dense fallback)",
        ("taken",),
    )
    mov_h = reg.histogram(
        f"{p}_movers_per_step",
        "Movers per step (sent + backlog) on sparse-capable loops",
        edges=MOVERS_EDGES,
    )
    cap_g = reg.gauge(
        f"{p}_capacity_rows",
        "Latest ratcheted capacity per budget (capacity_grow /"
        " mover_cap_grow events)",
        ("which",),
    )
    wire = reg.counter(
        f"{p}_exchange_wire_bytes",
        "Scheduled canonical-exchange wire bytes by resolved engine"
        " (redistribute events; pool width x row bytes x shards)",
        ("engine",),
    )
    alerts = reg.counter(
        f"{p}_alerts",
        "Health-rule findings journaled as alert events",
        ("rule", "severity"),
    )
    flow_moved = reg.gauge(
        f"{p}_flow_moved_rows",
        "Cumulative off-diagonal rows moved (latest flow_snapshot)",
    )
    flow_imb = reg.gauge(
        f"{p}_flow_imbalance",
        "Max/mean population imbalance (latest flow_snapshot; 1.0 ="
        " balanced)",
    )
    flow_pop = reg.gauge(
        f"{p}_rank_population",
        "Live rows per vrank (latest flow_snapshot population leaf)",
        ("vrank",),
    )
    roofline_g = reg.gauge(
        f"{p}_roofline_achieved_fraction",
        "Analytic-roofline predicted/measured step-time fraction per"
        " program (latest roofline event; 1.0 = at the roof)",
        ("program", "phase"),
    )
    profile_c = reg.counter(
        f"{p}_profile_sessions",
        "Profiler trace sessions attempted (profile_session events;"
        " armed or degraded alike)",
    )
    state_nan = reg.counter(
        f"{p}_state_nan",
        "Live rows with non-finite components over the journaled"
        " state_health window, per field (any nonzero is corruption)",
        ("field",),
    )
    state_oob = reg.counter(
        f"{p}_state_oob",
        "Live rows with positions outside the probe's domain box over"
        " the journaled state_health window",
    )
    state_live = reg.gauge(
        f"{p}_state_live_rows",
        "Total live particle rows at the latest probed step"
        " (state_health events)",
    )
    state_res = reg.gauge(
        f"{p}_state_residual",
        "Exact conservation residual (live + dropped - initial) at the"
        " latest probed step; nonzero = unaccounted row loss/creation",
    )

    saw_migrate = saw_flow = saw_roofline = saw_state = False
    for kind, data in events:
        if kind == "migrate_step":
            saw_migrate = True
            for d in ("sent", "received", "backlog", "dropped_recv"):
                if d in data:
                    rows.labels(direction=d).inc(int(data[d]))
            if "population" in data:
                pop_g.labels().set(int(data["population"]))
            if "backlog" in data:
                back_g.labels().set(int(data["backlog"]))
        elif kind == "step_time":
            if "seconds" in data:
                st_h.labels().observe(float(data["seconds"]))
        elif kind == "step_latency":
            if "seconds" in data:
                lat_h.labels().observe(float(data["seconds"]))
            drop_h.labels().observe(int(data.get("dropped", 0)))
        elif kind == "restore":
            corrupt_c.labels().inc(int(data.get("snapshots_skipped", 0) or 0))
        elif kind == "fast_path":
            fp_total.labels(taken=int(data.get("taken", 0))).inc()
            if "movers" in data:
                mov_h.labels().observe(int(data["movers"]))
        elif kind == "capacity_grow":
            if "which" in data and "new" in data:
                cap_g.labels(which=data["which"]).set(int(data["new"]))
        elif kind == "mover_cap_grow":
            if "new" in data:
                cap_g.labels(which="mover").set(int(data["new"]))
        elif kind == "redistribute":
            if "wire_bytes" in data:
                wire.labels(
                    engine=data.get("engine", "unknown")
                ).inc(int(data["wire_bytes"]))
        elif kind == "alert":
            alerts.labels(
                rule=data.get("rule", "unknown"),
                severity=data.get("severity", "unknown"),
            ).inc()
        elif kind == "flow_snapshot":
            saw_flow = True
            if "moved_rows_total" in data:
                flow_moved.labels().set(int(data["moved_rows_total"]))
            if "imbalance" in data:
                flow_imb.labels().set(float(data["imbalance"]))
            if data.get("population") is not None:
                # latest snapshot wins outright: drop stale vrank
                # children first so a shrunk rank count can't leave
                # ghost gauges behind
                flow_pop._children.clear()
                for vr, rows_live in enumerate(data["population"]):
                    flow_pop.labels(vrank=vr).set(int(rows_live))
        elif kind == "roofline":
            if data.get("achieved_fraction") is not None:
                saw_roofline = True
                roofline_g.labels(
                    program=data.get("program", "unknown"),
                    phase=data.get("phase", "total"),
                ).set(float(data["achieved_fraction"]))
        elif kind == "profile_session":
            profile_c.labels().inc()
        elif kind == "state_health":
            saw_state = True
            state_nan.labels(field="pos").inc(int(data.get("nan_pos", 0)))
            state_nan.labels(field="vel").inc(int(data.get("nan_vel", 0)))
            state_oob.labels().inc(int(data.get("oob", 0)))
            if "live" in data:
                state_live.labels().set(int(data["live"]))
            if "residual" in data:
                state_res.labels().set(int(data["residual"]))
        elif kind == "store_window":
            # compacted state_health windows keep feeding the corrupt-
            # row totals after the raw per-step rows are gone
            st = data.get("state")
            if st:
                saw_state = True
                state_nan.labels(field="pos").inc(int(st.get("nan_pos", 0)))
                state_nan.labels(field="vel").inc(int(st.get("nan_vel", 0)))
                state_oob.labels().inc(int(st.get("oob", 0)))
                if st.get("live_last") is not None:
                    state_live.labels().set(int(st["live_last"]))
                if st.get("residual_last") is not None:
                    state_res.labels().set(int(st["residual_last"]))
    # gauges with no samples yet would render a misleading 0 — only
    # materialize the step-scoped gauges once their kind has appeared
    if not saw_migrate:
        for fam in (pop_g, back_g):
            fam._children.clear()
    if not saw_flow:
        for fam in (flow_moved, flow_imb, flow_pop):
            fam._children.clear()
    if not saw_roofline:
        roofline_g._children.clear()
    if not saw_state:
        for fam in (state_live, state_res):
            fam._children.clear()
    return reg
