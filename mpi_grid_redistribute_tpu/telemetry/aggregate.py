"""Multi-host journal aggregation: merge per-process shards pod-wide.

A pod job runs one process per host; each writes its own journal shard
(``StepRecorder.to_jsonl`` — every line tagged ``host``/``pid``). This
module merges those shards into one pod-wide event stream so the
single-process observability stack (FlowAccumulator, HealthMonitor,
``exchange_report``, the metrics plane) runs unchanged over the whole
pod.

Merge semantics:

* **Monotonic-clock alignment.** Within a shard, ``seq`` is the truth
  of ordering; wall clocks wobble (NTP steps, clock skew between
  hosts). Each shard's times are first repaired to be monotone
  non-decreasing (a backward step is clamped to the previous event's
  time), optionally re-based to the shard's own start
  (``align="start"`` — comparable offsets when hosts' wall clocks
  disagree by more than the run length), then shards are k-way merged
  on aligned time with ``(host, pid, seq)`` as the tie-break. Intra-
  shard order is always preserved exactly.
* **Exact counts.** ``MergedJournal.counts()`` sums the per-shard
  per-kind counters, so pod-wide totals equal the sum of shard totals
  by construction (tested as the merge-equals-sum property).

Scrape-path purity: host-only, no jax imports (same contract as
:mod:`.metrics`).
"""

from __future__ import annotations

# gridlint: scrape-path

import json
import types
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import recorder as recorder_lib

# envelope keys a JSONL line may carry beyond the payload
_ENVELOPE = ("seq", "time", "kind", "host", "pid")


class Shard:
    """One process's journal: identity plus decoded event rows."""

    def __init__(self, host: str, pid: int, rows: List[dict]):
        self.host = str(host)
        self.pid = int(pid)
        self.rows = rows  # [{seq, time, kind, **payload}] in seq order

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.rows:
            out[r["kind"]] = out.get(r["kind"], 0) + 1
        return out


def _shard_from_lines(lines, fallback_host, fallback_pid) -> Shard:
    rows = []
    host, pid = fallback_host, fallback_pid
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        d = json.loads(ln)
        host = d.pop("host", host)
        pid = d.pop("pid", pid)
        rows.append(d)
    rows.sort(key=lambda r: r.get("seq", 0))
    return Shard(host, pid, rows)


def _coerce_shard(source, idx: int) -> Shard:
    """Accept a JSONL path, an open text file, a ``StepRecorder``, or an
    iterable of decoded dicts."""
    if isinstance(source, recorder_lib.StepRecorder):
        rows = [
            {"seq": e.seq, "time": e.time, "kind": e.kind, **e.data}
            for e in source.events()
        ]
        return Shard(source.host, source.pid, rows)
    fallback = (f"shard{idx}", 0)
    if isinstance(source, (str, bytes)):
        with open(source) as f:
            return _shard_from_lines(f, *fallback)
    if hasattr(source, "read"):
        return _shard_from_lines(source, *fallback)
    # iterable of decoded dicts
    lines = [json.dumps(d) for d in source]
    return _shard_from_lines(lines, *fallback)


class MergedJournal:
    """The pod-wide event stream plus per-shard attribution.

    ``events`` rows carry the shard identity (``host``/``pid``), the
    original ``seq``/``time``, the aligned merge key ``t_aligned``, and
    the flat payload — directly consumable by
    :func:`..metrics.from_journal`.
    """

    def __init__(self, shards: List[Shard], events: List[dict],
                 align: str):
        self.shards = shards
        self._events = events
        self.align = align

    def events(self, kind: Optional[str] = None) -> List[dict]:
        if kind is None:
            return list(self._events)
        return [e for e in self._events if e["kind"] == kind]

    def __len__(self) -> int:
        return len(self._events)

    def counts(self) -> Dict[str, int]:
        """Pod-wide per-kind totals == sum over shards (by construction;
        the merge-equals-sum test asserts it end to end)."""
        out: Dict[str, int] = {}
        for sh in self.shards:
            for k, n in sh.counts().items():
                out[k] = out.get(k, 0) + n
        return out

    def per_shard_counts(self) -> Dict[Tuple[str, int], Dict[str, int]]:
        return {(sh.host, sh.pid): sh.counts() for sh in self.shards}

    # -- projections into the single-process observability stack --------

    def to_recorder(
        self,
        pod_steps: bool = False,
        capacity: Optional[int] = None,
    ) -> recorder_lib.StepRecorder:
        """Replay the merged stream into a fresh ``StepRecorder`` (host
        tag ``"pod"``) so HealthMonitor / trace export / metrics replay
        run over the pod-wide journal.

        ``pod_steps=True`` additionally *sums* same-step ``migrate_step``
        events across shards into one pod-wide event per step (scalar
        counters added; ``*_per_rank`` vectors concatenated in shard
        order — each shard covers its own rank slice of the pod), which
        is what the backlog/drop health rules should judge: a pod with
        one hot shard must page on pod totals, not per-shard slivers.
        Non-step events keep their shard identity as ``host``/``pid``
        payload keys."""
        cap = capacity if capacity is not None else max(
            4096, 2 * len(self._events) or 4096
        )
        rec = recorder_lib.StepRecorder(capacity=cap, host="pod", pid=0)
        if not pod_steps:
            for e in self._events:
                d = self._payload(e)
                rec.record_at(
                    e["kind"], e.get("t_aligned"),
                    host=e["host"], pid=e["pid"], **d,
                )
            return rec
        # group migrate_step by step index across shards
        groups: Dict[int, List[dict]] = {}
        out_rows: List[Tuple[float, int, dict]] = []
        for order, e in enumerate(self._events):
            if e["kind"] == "migrate_step" and "step" in e:
                groups.setdefault(int(e["step"]), []).append(e)
            else:
                d = self._payload(e)
                d.update(host=e["host"], pid=e["pid"])
                out_rows.append(
                    (e.get("t_aligned", 0.0), order,
                     {"kind": e["kind"], "data": d})
                )
        for step, evs in groups.items():
            agg = {"step": step}
            for key in (
                "sent", "received", "backlog", "dropped_recv", "population"
            ):
                if any(key in self._payload(e) for e in evs):
                    agg[key] = sum(
                        int(self._payload(e).get(key, 0)) for e in evs
                    )
            for key in (
                "sent_per_rank", "received_per_rank", "population_per_rank"
            ):
                if all(key in self._payload(e) for e in evs):
                    vec: List[int] = []
                    for e in evs:
                        vec.extend(int(x) for x in self._payload(e)[key])
                    agg[key] = vec
            t = max(e.get("t_aligned", 0.0) for e in evs)
            out_rows.append(
                (t, len(self._events) + step,
                 {"kind": "migrate_step", "data": agg})
            )
        out_rows.sort(key=lambda r: (r[0], r[1]))
        for t, _, row in out_rows:
            rec.record_at(row["kind"], t, **row["data"])
        return rec

    def pod_stats(self):
        """Pod-wide ``MigrateStats``-shaped view of the merged
        ``migrate_step`` stream, for ``exchange_report`` /
        ``summarize_migrate``.

        When every shard journaled ``rank_totals=True`` vectors, the
        rank axis is the pod's full rank space (shards concatenated in
        shard order): arrays are ``[S, R_pod]``. Otherwise each shard
        collapses to one column (its per-step totals): ``[S, n_shards]``.
        Steps present in only some shards are zero-filled for the
        missing shards. Raises ``ValueError`` when no shard journaled
        migrate steps."""
        per_shard: List[Dict[int, dict]] = []
        for sh in self.shards:
            by_step = {
                int(r["step"]): r
                for r in sh.rows
                if r["kind"] == "migrate_step" and "step" in r
            }
            if by_step:
                per_shard.append(by_step)
        if not per_shard:
            raise ValueError(
                "no migrate_step events in any shard — nothing to"
                " aggregate into pod stats"
            )
        steps = sorted({s for by in per_shard for s in by})
        ranked = all(
            "sent_per_rank" in r for by in per_shard for r in by.values()
        )
        widths = []
        for by in per_shard:
            widths.append(
                len(next(iter(by.values()))["sent_per_rank"]) if ranked
                else 1
            )
        cols = sum(widths)
        names = ("sent", "received", "backlog", "dropped_recv",
                 "population")
        arrs = {n: np.zeros((len(steps), cols), np.int64) for n in names}
        for si, step in enumerate(steps):
            c0 = 0
            for by, w in zip(per_shard, widths):
                r = by.get(step)
                if r is not None:
                    for n in names:
                        if ranked and f"{n}_per_rank" in r:
                            arrs[n][si, c0:c0 + w] = r[f"{n}_per_rank"]
                        elif n in r:
                            # totals only: spread is unknowable, put the
                            # shard total in its single column
                            arrs[n][si, c0] = int(r[n])
                c0 += w
        return types.SimpleNamespace(steps=steps, **arrs)

    def flow_snapshot(self, k: int = 5) -> dict:
        """Pod-wide flow gauges merged from the shards' latest
        ``flow_snapshot`` events: moved totals summed, ``top_pairs``
        re-ranked across shards (rank indices are shard-local — pairs
        keep a ``host`` tag instead of being offset, since shards don't
        journal their rank base). Raises ``ValueError`` when no shard
        journaled a snapshot."""
        snaps = []
        for sh in self.shards:
            rows = [r for r in sh.rows if r["kind"] == "flow_snapshot"]
            if rows:
                snaps.append((sh, rows[-1]))
        if not snaps:
            raise ValueError("no flow_snapshot events in any shard")
        pairs = []
        for sh, s in snaps:
            for src, dst, rows in s.get("top_pairs", []):
                pairs.append([sh.host, int(src), int(dst), int(rows)])
        pairs.sort(key=lambda p: -p[3])
        return {
            "shards": len(snaps),
            "n_ranks": sum(int(s.get("n_ranks", 0)) for _, s in snaps),
            "moved_rows_total": sum(
                int(s.get("moved_rows_total", 0)) for _, s in snaps
            ),
            "imbalance": max(
                float(s.get("imbalance", 1.0)) for _, s in snaps
            ),
            "top_pairs": pairs[:k],
        }

    @staticmethod
    def _payload(e: dict) -> dict:
        return {
            k: v for k, v in e.items()
            if k not in _ENVELOPE and k != "t_aligned"
        }


def merge_journals(sources, align: str = "wall") -> MergedJournal:
    """Merge journal shards into one pod-wide :class:`MergedJournal`.

    ``sources`` — JSONL paths, open files, ``StepRecorder`` instances,
    or iterables of decoded event dicts (mixable). ``align``:

    * ``"wall"`` (default) — shards share a clock domain (same host, or
      NTP-synced pod); merge on repaired wall time.
    * ``"start"`` — re-base each shard to its own first event (merge on
      run-relative offsets); use when hosts' clocks disagree by more
      than the run length.
    """
    if align not in ("wall", "start"):
        raise ValueError(f"align must be 'wall' or 'start', got {align!r}")
    shards = [_coerce_shard(s, i) for i, s in enumerate(sources)]
    if not shards:
        raise ValueError("merge_journals: no sources")
    merged: List[dict] = []
    for sh in shards:
        t0 = None
        prev = -float("inf")
        for r in sh.rows:
            t = float(r.get("time", 0.0))
            if t0 is None:
                t0 = t
            # monotone repair: a backward wall-clock step cannot reorder
            # events within the shard (seq is the intra-shard truth)
            prev = max(prev, t)
            e = dict(r)
            e["host"], e["pid"] = sh.host, sh.pid
            e["t_aligned"] = prev - (t0 if align == "start" else 0.0)
            merged.append(e)
    merged.sort(
        key=lambda e: (
            e["t_aligned"], e["host"], e["pid"], e.get("seq", 0)
        )
    )
    return MergedJournal(shards, merged, align)
