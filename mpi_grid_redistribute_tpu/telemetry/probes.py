"""Host side of the state-health observatory (ISSUE 20).

The in-graph half lives in ``ops/statehealth.py``: the resident and
pipelined macro-steps fold a per-step state summary (live rows, NaN/Inf
counts, out-of-bounds positions, the conservation residual, optional
moments) into their scan ys. This module is everything the *host* does
with those summaries:

* :class:`ProbeConfig` — the static tier knob (``off`` / ``counters``
  / ``moments``). Frozen and hashable so it joins the driver's
  compiled-macro cache key: changing the tier is a retrace, never a
  silent reuse of the wrong program. ``off`` is the default and is
  bit-identical zero-cost — the builders emit the exact unprobed
  program (``tests/test_probes.py`` pins jaxpr equality).
* :func:`record_probe_steps` — the chunk-boundary bridge (the
  ``record_chunk_steps`` pattern): one ``state_health`` journal event
  per scanned step, from already-fetched host arrays.
* :func:`summarize_host` — the numpy mirror of the in-graph summary,
  bit-compatible in every counter, for the driver's eager path (numpy
  backend, singleton fault chunks, overflow re-runs) so probed runs
  journal the same event stream whatever path executed the step.

Scrape-path purity: jax-free (G007) — ``tests/test_metrics.py`` loads
this module with jax absent. Event schema: telemetry/SCHEMA.md
``state_health``; the ``nan_detected`` / ``conservation_drift`` /
``bounds_violation`` health rules (telemetry/health.py) evaluate over
these events.
"""

from __future__ import annotations

# gridlint: scrape-path

import dataclasses

import numpy as np

#: Probe tiers, cheapest first. ``off`` emits nothing (bit-identical
#: program); ``counters`` adds five int32 scalars per step;
#: ``moments`` adds per-axis position extents and the velocity second
#: moment on top.
TIERS = ("off", "counters", "moments")


@dataclasses.dataclass(frozen=True)
class ProbeConfig:
    """Static probe configuration (hashable: cache-key safe).

    ``tier`` selects what the in-graph pass computes; bounds give the
    domain box the ``oob`` counter checks positions against (the
    service domain is the periodic unit box, so ``[0, 1)``)."""

    tier: str = "off"
    lo: float = 0.0
    hi: float = 1.0

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(
                f"unknown probe tier {self.tier!r} (choose from {TIERS})"
            )
        if not self.hi > self.lo:
            raise ValueError(
                f"probe bounds must satisfy lo < hi, got "
                f"[{self.lo}, {self.hi})"
            )

    @property
    def armed(self) -> bool:
        return self.tier != "off"

    @property
    def moments(self) -> bool:
        return self.tier == "moments"


def record_probe_steps(recorder, first_step: int, probe) -> int:
    """Feed one chunk's stacked probe ys into ``recorder`` as one
    ``state_health`` event per step.

    ``probe`` is the ``ys["probe"]`` dict from a probe-armed macro-step
    — leaves stacked ``[chunk]`` (scalars) or ``[chunk, ndim]``
    (moment vectors). Same host-transfer contract as
    :func:`.recorder.record_chunk_steps`: the caller passes
    already-fetched host values at a chunk boundary, never device
    arrays from a hot loop. Steps are numbered ``first_step,
    first_step + 1, ...`` — the post-increment numbering every other
    per-step event kind uses. Returns the number of events recorded."""
    live = np.asarray(probe["live"])
    nan_pos = np.asarray(probe["nan_pos"])
    nan_vel = np.asarray(probe["nan_vel"])
    oob = np.asarray(probe["oob"])
    residual = np.asarray(probe["residual"])
    pos_min = probe.get("pos_min")
    pos_max = probe.get("pos_max")
    vel_m2 = probe.get("vel_m2")
    n = int(live.shape[0])
    for i in range(n):
        extra = {}
        if pos_min is not None:
            extra["pos_min"] = [float(x) for x in np.asarray(pos_min)[i]]
            extra["pos_max"] = [float(x) for x in np.asarray(pos_max)[i]]
            extra["vel_m2"] = float(np.asarray(vel_m2)[i])
        recorder.record(
            "state_health",
            step=int(first_step) + i,
            live=int(live[i]),
            nan_pos=int(nan_pos[i]),
            nan_vel=int(nan_vel[i]),
            oob=int(oob[i]),
            residual=int(residual[i]),
            **extra,
        )
    return n


def summarize_host(
    pos, vel, count, initial_live, cum_dropped, cfg: ProbeConfig
):
    """Numpy mirror of ``ops.statehealth.summarize`` for the eager
    driver path: one ``state_health`` payload dict (host scalars,
    ready for ``recorder.record``) from prefix-valid ``[R * cap,
    ndim]`` state. Counter-exact against the in-graph pass — a step
    executed eagerly (fault chunk, overflow re-run, numpy backend)
    journals the same numbers the resident scan would have."""
    pos = np.asarray(pos)
    vel = np.asarray(vel)
    count = np.asarray(count)
    cap = pos.shape[0] // count.shape[0]
    mask = (
        np.arange(cap, dtype=np.int32)[None, :] < count[:, None]
    ).reshape(-1)
    with np.errstate(invalid="ignore"):
        bad_pos = ~np.isfinite(pos)
        bad_vel = ~np.isfinite(vel)
        out = (pos < cfg.lo) | (pos >= cfg.hi)
    live = int(count.sum())
    payload = {
        "live": live,
        "nan_pos": int(np.sum(np.any(bad_pos, axis=-1) & mask)),
        "nan_vel": int(np.sum(np.any(bad_vel, axis=-1) & mask)),
        "oob": int(np.sum(np.any(out, axis=-1) & mask)),
        "residual": live + int(cum_dropped) - int(initial_live),
    }
    if cfg.moments:
        m = mask[:, None]
        posf = pos.astype(np.float32)
        velf = vel.astype(np.float32)
        payload["pos_min"] = [
            float(x)
            for x in np.min(np.where(m, posf, np.float32(np.inf)), axis=0)
        ]
        payload["pos_max"] = [
            float(x)
            for x in np.max(
                np.where(m, posf, np.float32(-np.inf)), axis=0
            )
        ]
        payload["vel_m2"] = float(
            np.sum(np.where(m, velf * velf, np.float32(0.0)))
        )
    return payload
