"""Bench regression guard: min-of-k timing protocol + history comparison.

Round 5 shipped a 7.9% throughput regression (799.6M → 736.4M pps) that
nobody noticed because bench.py had no variance protocol and no history
comparison (VERDICT Weak #4). This module closes both gaps:

* :func:`min_of_k` — the timing protocol: k independent estimates from an
  already-compiled measurement, keep the min (noise on a quiet machine is
  one-sided: interference only ever ADDS time) and report the spread
  ``(max - min)/min`` so a capture carries its own noise floor. A 10%
  regression gate over captures whose spread is 30% is meaningless; the
  spread in the JSON is what makes the gate honest.
* :func:`check_capture` — the gate: compare a current capture against the
  committed ``BENCH_r*.json`` history and fail (nonzero exit from the
  CLI, report lines either way) when throughput drops more than
  ``threshold`` below the BEST committed value. Best, not latest: a slow
  drift of back-to-back sub-threshold regressions must not ratchet the
  reference down with it.

CLI (wired as ``make bench-check``)::

    python -m mpi_grid_redistribute_tpu.telemetry.regress \
        [--current CAPTURE.json] [--history 'BENCH_r*.json'] \
        [--threshold 0.10]

With no ``--current``, the newest history capture is checked against the
rest — the self-test mode CI runs on every commit.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Metrics the gate watches: name -> direction. "higher" fails when the
# current value drops below best*(1-threshold); "lower" (times) fails
# when it rises above best*(1+threshold).
GUARDED_METRICS: Dict[str, str] = {
    "value": "higher",        # particles/sec/chip — the headline
    "ms_per_step": "lower",
    "exchange_bytes_per_sec": "higher",
    # the BASELINE metric's second head: achieved fraction of the
    # exchange-domain roof. Guarded so a refactor cannot silently trade
    # wire efficiency for pps (same rows at lower utilization = the step
    # got slower elsewhere). r01/r02 predate the field -> skipped there.
    "exchange_bw_util": "higher",
    # the stress capture's bw_util: the headline workload is
    # compute-bound at 2% migration, so only the nested full-reshuffle
    # stress run (bench.py "stress" key <- config7_stress) says whether
    # the exchange itself kept its roof-side headroom. Skipped against
    # captures that predate the stress field.
    "stress_bw_util": "higher",
}

# nested fallbacks: a metric missing at the top level of the parsed
# bench line is pulled from a nested dict instead — newer captures carry
# the merged exchange_report under "report" (unprefixed keys) and the
# full-reshuffle capture under "stress"
_NESTED_KEYS: Dict[str, Tuple[str, str]] = {
    "exchange_bw_util": ("report", "bw_util"),
    "exchange_bytes_per_sec": ("report", "exchange_bytes_per_sec"),
    "stress_bw_util": ("stress", "bw_util"),
}


def min_of_k(sample: Callable[[], float], k: int = 5) -> Dict[str, float]:
    """Run ``sample()`` k times; return min + spread statistics.

    ``sample`` must return one timing estimate (seconds or any monotone
    cost) from an ALREADY-COMPILED measurement — e.g. a closure over
    :func:`..utils.profiling.scan_time_per_step`'s compiled loops — so
    the k calls measure run-to-run noise, not compile noise. Returns
    ``{min, max, mean, spread, k, values}``; ``spread`` is
    ``(max-min)/min`` (0 when min is 0)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    values = [float(sample()) for _ in range(k)]
    lo, hi = min(values), max(values)
    return {
        "min": lo,
        "max": hi,
        "mean": sum(values) / k,
        "spread": (hi - lo) / lo if lo > 0 else 0.0,
        "k": k,
        "values": values,
    }


def extract_metrics(capture: dict) -> Optional[Dict[str, float]]:
    """Pull the guarded metrics out of one capture.

    Accepts either a raw bench JSON line (the dict bench.py prints) or a
    committed ``BENCH_r*.json`` wrapper ``{n, cmd, rc, tail, parsed}``.
    Returns None when the capture carries no bench line (e.g. a failed
    run with ``parsed: null``) — callers skip those."""
    parsed = capture.get("parsed", capture)
    if not isinstance(parsed, dict) or "value" not in parsed:
        return None
    out = {}
    for name in GUARDED_METRICS:
        v = parsed.get(name)
        if v is None and name in _NESTED_KEYS:
            outer, inner = _NESTED_KEYS[name]
            nested = parsed.get(outer)
            if isinstance(nested, dict):
                v = nested.get(inner)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def check_capture(
    current: dict,
    history: Sequence[dict],
    threshold: float = 0.10,
) -> Tuple[bool, List[str]]:
    """Gate one capture against history; returns (ok, report_lines).

    ``current`` and each history entry may be raw bench lines or
    ``BENCH_r*`` wrappers. For every guarded metric present in BOTH the
    current capture and at least one history capture, compare against the
    best historical value; a relative change worse than ``threshold`` in
    the metric's bad direction fails the gate. Metrics missing from
    either side are reported as skipped, never failed — a new metric
    must be able to land before it has history."""
    lines: List[str] = []
    cur = extract_metrics(current)
    if cur is None:
        return False, ["FAIL: current capture has no parsed bench metrics"]
    hists = [m for m in (extract_metrics(h) for h in history) if m]
    if not hists:
        return False, ["FAIL: no usable history captures"]
    ok = True
    for name, direction in GUARDED_METRICS.items():
        vals = [h[name] for h in hists if name in h]
        if name not in cur or not vals:
            lines.append(f"skip  {name}: no {'current' if name not in cur else 'history'} value")
            continue
        best = max(vals) if direction == "higher" else min(vals)
        now = cur[name]
        if best == 0:
            lines.append(f"skip  {name}: zero best in history")
            continue
        # signed relative change, positive = worse
        delta = (best - now) / best if direction == "higher" else (now - best) / best
        verdict = "FAIL" if delta > threshold else ("ok  " if delta <= 0 else "warn")
        if delta > threshold:
            ok = False
        # Δ is printed with negative = worse regardless of direction
        lines.append(
            f"{verdict}  {name}: current {now:.6g} vs best {best:.6g} "
            f"(Δ {-delta*100:+.1f}%, threshold {threshold*100:.0f}%, "
            f"n_history={len(vals)})"
        )
    return ok, lines


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Bench regression guard: compare a capture against "
        "committed BENCH_r*.json history (>threshold regressions fail)."
    )
    p.add_argument(
        "--current",
        help="capture to check (bench JSON line or BENCH_r wrapper); "
        "default: the newest history file, checked against the rest",
    )
    p.add_argument(
        "--history",
        default="BENCH_r*.json",
        help="glob of committed captures (default BENCH_r*.json)",
    )
    p.add_argument("--threshold", type=float, default=0.10)
    args = p.parse_args(argv)

    paths = sorted(glob.glob(args.history))
    if not paths:
        print(f"bench-check FAIL: no history matches {args.history!r}")
        return 2
    if args.current:
        current = _load(args.current)
        hist_paths = paths
    else:
        # self-test mode: newest (by round suffix = sorted order) vs rest
        current = _load(paths[-1])
        hist_paths = paths[:-1]
        if not hist_paths:
            print("bench-check ok: single capture, nothing to compare")
            return 0
        print(f"checking {paths[-1]} against {len(hist_paths)} earlier captures")
    history = [_load(pth) for pth in hist_paths]
    ok, lines = check_capture(current, history, args.threshold)
    for ln in lines:
        print("  " + ln)
    print(f"bench-check {'ok' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
