"""Bench regression guard: min-of-k timing protocol + history comparison.

Round 5 shipped a 7.9% throughput regression (799.6M → 736.4M pps) that
nobody noticed because bench.py had no variance protocol and no history
comparison (VERDICT Weak #4). This module closes both gaps:

* :func:`min_of_k` — the timing protocol: k independent estimates from an
  already-compiled measurement, keep the min (noise on a quiet machine is
  one-sided: interference only ever ADDS time) and report the spread
  ``(max - min)/min`` so a capture carries its own noise floor. A 10%
  regression gate over captures whose spread is 30% is meaningless; the
  spread in the JSON is what makes the gate honest.
* :func:`check_capture` — the hard gate: compare a current capture
  against the committed ``BENCH_r*.json`` history and fail (report lines
  either way) when throughput drops more than ``threshold`` below the
  BEST committed value. Best, not latest: a slow drift of back-to-back
  sub-threshold regressions must not ratchet the reference down with it.
* :func:`classify_capture` — the noise-aware layer on top (ISSUE 5):
  instead of one binary threshold, each delta is labeled
  ``OK`` / ``WOBBLE`` / ``WARN`` / ``REGRESSION`` against a per-metric
  noise floor derived from the captures' own recorded ``timing_spread``
  (the min-of-k spread above). The calibration case is r04→r05: the
  headline moved 799.6M → 736.4M pps (−7.9%) with *byte-identical*
  ``exchange_bytes_per_step`` — pure wall-clock wobble that the hard
  gate can neither flag as noise nor tell apart from a real hot-path
  regression. The classifier labels it WOBBLE; a 2× slowdown labels
  REGRESSION. Only REGRESSION fails the CLI gate.
* :func:`env_fingerprint` — captures record the environment they ran in
  (jax/numpy versions, backend, device kind, flags); the classifier
  notes fingerprint drift vs the best capture, because "the machine
  changed" is the most common non-regression explanation for a WARN.

CLI (wired as ``make bench-check``)::

    python -m mpi_grid_redistribute_tpu.telemetry.regress \
        [--current CAPTURE.json] [--history 'BENCH_r*.json'] \
        [--threshold 0.10] [--legacy]

With no ``--current``, the newest history capture is checked against the
rest — the self-test mode CI runs on every commit. ``--legacy`` restores
the pre-classifier binary gate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import platform as _platform
import sys
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Metrics the gate watches: name -> direction. "higher" fails when the
# current value drops below best*(1-threshold); "lower" (times) fails
# when it rises above best*(1+threshold).
GUARDED_METRICS: Dict[str, str] = {
    "value": "higher",        # particles/sec/chip — the headline
    "ms_per_step": "lower",
    "exchange_bytes_per_sec": "higher",
    # the BASELINE metric's second head: achieved fraction of the
    # exchange-domain roof. Guarded so a refactor cannot silently trade
    # wire efficiency for pps (same rows at lower utilization = the step
    # got slower elsewhere). r01/r02 predate the field -> skipped there.
    "exchange_bw_util": "higher",
    # the stress capture's bw_util: the headline workload is
    # compute-bound at 2% migration, so only the nested full-reshuffle
    # stress run (bench.py "stress" key <- config7_stress) says whether
    # the exchange itself kept its roof-side headroom. Skipped against
    # captures that predate the stress field.
    "stress_bw_util": "higher",
    # the service soak's sustained throughput with the checkpoint
    # cadence ON (bench.py "soak" key <- config8_soak): guards the full
    # service loop — host drift + public-API redistribute + async
    # snapshot writer — so durability cannot silently get expensive.
    # Skipped against captures that predate the soak field.
    "soak_pps": "higher",
    # scheduled canonical-exchange wire bytes per step (ISSUE 7
    # count-driven engines): pool width x row bytes x shards, the cost
    # the mover-sparse wire exists to shrink. Guarded LOWER so a change
    # cannot silently re-widen the pool back toward the dense [K, R*C]
    # schedule while pps holds. Auto-arms: skipped against histories
    # that predate the field (the PR 3 pattern).
    "exchange_wire_bytes_per_step": "lower",
    # per-domain split of the hierarchical two-level schedule's wire
    # (ISSUE 19, bench/config4_drift.hierarchical_wire_capture on the
    # virtual two-pod mesh): the DCN column is the bytes the slow
    # cross-pod link carries (staged per-(pod,pod) condensed blocks) —
    # guarded LOWER so a change cannot silently re-widen the cross
    # stage back toward dense fan-out; the ICI column guards the
    # intra-pod neighbor pool + fanout the same way. Auto-arm: skipped
    # against histories that predate the fields (the PR 7 pattern).
    "exchange_dcn_bytes_per_step": "lower",
    "exchange_ici_bytes_per_step": "lower",
    # the closed-loop adaptive-rebalance leg's steady-state ms/step
    # under sustained drift bias (bench.py "rebalance" key <-
    # config4_drift.run_rebalance, loop ON): guards the whole
    # plan->guard->apply path — a regression here means the one-shot
    # remap stopped paying for itself. Auto-arms: skipped against
    # histories that predate the field (the PR 3 pattern).
    "rebalance_drift_ms": "lower",
    # the resident chunked-stepping capture's service-mode throughput
    # (bench.py "service" key <- config10_service, chunk=64 on the
    # 8-vrank CPU mesh): guards the lax.scan macro-step path — a
    # regression here means per-step host syncs crept back into the
    # chunk interior. Auto-arms: skipped against histories that predate
    # the field (the PR 3 pattern).
    "service_pps": "higher",
    # the software-pipelined macro-step's throughput at the same head
    # chunk (bench.py "service" key <- config10_service, ISSUE 12):
    # guards the overlapped scan body — a regression here means the
    # land->drift->bin dependency chain crept back into the steady
    # state, or the fused free-stack landing split into two scatters.
    # Auto-arms: skipped against histories that predate the field.
    "pipeline_pps": "higher",
    # the state-health probe pass's cost ratio (bench.py "service" key
    # <- config10_service, ISSUE 20): probed/unprobed step time at the
    # head chunk, 1.0 = free. Guarded LOWER as the ratio (the raw
    # paired-delta median is centred on zero, where relative-change
    # math is meaningless) — the hard <= 2% budget is config10's own
    # gate; this guard catches a probe pass that quietly grows past its
    # history. Auto-arms: skipped against histories that predate the
    # field.
    "probe_cost_factor": "lower",
}

# nested fallbacks: a metric missing at the top level of the parsed
# bench line is pulled from a nested dict instead — newer captures carry
# the merged exchange_report under "report" (unprefixed keys) and the
# full-reshuffle capture under "stress"
_NESTED_KEYS: Dict[str, Tuple[str, str]] = {
    "exchange_bw_util": ("report", "bw_util"),
    "exchange_bytes_per_sec": ("report", "exchange_bytes_per_sec"),
    "stress_bw_util": ("stress", "bw_util"),
    "soak_pps": ("soak", "value"),
    "exchange_wire_bytes_per_step": ("report", "wire_bytes_per_step"),
    "exchange_dcn_bytes_per_step": ("report", "dcn_bytes_per_step"),
    "exchange_ici_bytes_per_step": ("report", "ici_bytes_per_step"),
    "rebalance_drift_ms": ("rebalance", "steady_ms_per_step"),
    "service_pps": ("service", "value"),
    "pipeline_pps": ("service", "pipeline_pps"),
    "probe_cost_factor": ("service", "probe_cost_factor"),
}


def min_of_k(sample: Callable[[], float], k: int = 5) -> Dict[str, float]:
    """Run ``sample()`` k times; return min + spread statistics.

    ``sample`` must return one timing estimate (seconds or any monotone
    cost) from an ALREADY-COMPILED measurement — e.g. a closure over
    :func:`..utils.profiling.scan_time_per_step`'s compiled loops — so
    the k calls measure run-to-run noise, not compile noise. Returns
    ``{min, max, mean, spread, k, values}``; ``spread`` is
    ``(max-min)/min`` (0 when min is 0)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    values = [float(sample()) for _ in range(k)]
    lo, hi = min(values), max(values)
    return {
        "min": lo,
        "max": hi,
        "mean": sum(values) / k,
        "spread": (hi - lo) / lo if lo > 0 else 0.0,
        "k": k,
        "values": values,
    }


def extract_metrics(capture: dict) -> Optional[Dict[str, float]]:
    """Pull the guarded metrics out of one capture.

    Accepts either a raw bench JSON line (the dict bench.py prints) or a
    committed ``BENCH_r*.json`` wrapper ``{n, cmd, rc, tail, parsed}``.
    Returns None when the capture carries no bench line (e.g. a failed
    run with ``parsed: null``) — callers skip those."""
    parsed = capture.get("parsed", capture)
    if not isinstance(parsed, dict) or "value" not in parsed:
        return None
    out = {}
    for name in GUARDED_METRICS:
        v = parsed.get(name)
        if v is None and name in _NESTED_KEYS:
            outer, inner = _NESTED_KEYS[name]
            nested = parsed.get(outer)
            if isinstance(nested, dict):
                v = nested.get(inner)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def check_capture(
    current: dict,
    history: Sequence[dict],
    threshold: float = 0.10,
) -> Tuple[bool, List[str]]:
    """Gate one capture against history; returns (ok, report_lines).

    ``current`` and each history entry may be raw bench lines or
    ``BENCH_r*`` wrappers. For every guarded metric present in BOTH the
    current capture and at least one history capture, compare against the
    best historical value; a relative change worse than ``threshold`` in
    the metric's bad direction fails the gate. Metrics missing from
    either side are reported as skipped, never failed — a new metric
    must be able to land before it has history."""
    lines: List[str] = []
    cur = extract_metrics(current)
    if cur is None:
        return False, ["FAIL: current capture has no parsed bench metrics"]
    hists = [m for m in (extract_metrics(h) for h in history) if m]
    if not hists:
        return False, ["FAIL: no usable history captures"]
    ok = True
    for name, direction in GUARDED_METRICS.items():
        vals = [h[name] for h in hists if name in h]
        if name not in cur or not vals:
            lines.append(f"skip  {name}: no {'current' if name not in cur else 'history'} value")
            continue
        best = max(vals) if direction == "higher" else min(vals)
        now = cur[name]
        if best == 0:
            lines.append(f"skip  {name}: zero best in history")
            continue
        # signed relative change, positive = worse
        delta = (best - now) / best if direction == "higher" else (now - best) / best
        verdict = "FAIL" if delta > threshold else ("ok  " if delta <= 0 else "warn")
        if delta > threshold:
            ok = False
        # Δ is printed with negative = worse regardless of direction
        lines.append(
            f"{verdict}  {name}: current {now:.6g} vs best {best:.6g} "
            f"(Δ {-delta*100:+.1f}%, threshold {threshold*100:.0f}%, "
            f"n_history={len(vals)})"
        )
    return ok, lines


# ---------------------------------------------------------------------------
# Noise-aware classification (ISSUE 5).

# Spread substituted for captures that predate the min-of-k protocol
# (r01–r05 carry no timing_spread). Calibrated from the one measured
# wobble in the committed history: r04→r05 moved the headline 8.6% on
# byte-identical exchange work (BENCH_CONFIGS.md), so pre-spread
# captures are assumed ~8% noisy.
DEFAULT_SPREAD = 0.08
# Safety margin on the spread-derived floor: spread is (max-min)/min of
# k samples — an underestimate of the true run-to-run envelope for
# small k.
SPREAD_MARGIN = 1.25
# A delta is REGRESSION only beyond max(threshold, this factor × noise):
# clearly outside anything the captures' own variance can explain.
REGRESSION_FACTOR = 2.0

# classification labels, worst first
REGRESSION, WARN, WOBBLE, OK = "REGRESSION", "WARN", "WOBBLE", "OK"
_SEVERITY = {REGRESSION: 3, WARN: 2, WOBBLE: 1, OK: 0}

# fingerprint keys whose drift invalidates naive cross-capture deltas
_FP_COMPARE_KEYS = (
    "jax", "backend", "device_kind", "device_count", "xla_flags"
)


def env_fingerprint() -> Dict[str, object]:
    """The environment a capture ran in, for cross-capture comparisons.

    Recorded by bench.py under the ``env`` key of every capture. jax is
    probed only if importable (this module itself must stay importable
    on a host with no accelerator stack); device queries are best-effort
    — bench callers have already initialized the backend, so the normal
    path records real device kinds."""
    fp: Dict[str, object] = {
        "python": _platform.python_version(),
        "platform": sys.platform,
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }
    try:
        import numpy

        fp["numpy"] = numpy.__version__
    except ImportError:  # pragma: no cover
        pass
    try:
        import jax

        fp["jax"] = jax.__version__
        devs = jax.devices()
        fp["backend"] = devs[0].platform
        fp["device_kind"] = devs[0].device_kind
        fp["device_count"] = len(devs)
    except Exception:  # jax absent or backend init failed: still usable
        pass
    return fp


def _spread_of(capture: dict) -> Optional[float]:
    """The capture's own recorded min-of-k spread, if it has one."""
    parsed = capture.get("parsed", capture)
    if not isinstance(parsed, dict):
        return None
    v = parsed.get("timing_spread")
    return float(v) if isinstance(v, (int, float)) else None


def _env_of(capture: dict) -> Optional[dict]:
    parsed = capture.get("parsed", capture)
    if not isinstance(parsed, dict):
        return None
    env = parsed.get("env")
    return env if isinstance(env, dict) else None


def _progprofile_of(capture: dict) -> Optional[str]:
    """The progcheck static wire-model hash the capture was taken
    under (bench.py embeds analysis.baseline.progprofile_hash()), or
    None for captures that predate it."""
    parsed = capture.get("parsed", capture)
    if not isinstance(parsed, dict):
        return None
    h = parsed.get("progprofile_hash")
    return h if isinstance(h, str) else None


def noise_floor(
    current_spread: Optional[float],
    best_spread: Optional[float],
) -> Tuple[float, bool]:
    """Per-metric noise floor from the two captures being compared.

    ``SPREAD_MARGIN × max(spread_current, spread_best)``, substituting
    :data:`DEFAULT_SPREAD` for captures that predate the min-of-k
    protocol. Returns ``(floor, defaulted)`` — ``defaulted`` is True
    when either side used the substitute (the report says so, because a
    defaulted floor is an assumption, not a measurement)."""
    defaulted = current_spread is None or best_spread is None
    cur = DEFAULT_SPREAD if current_spread is None else float(current_spread)
    best = DEFAULT_SPREAD if best_spread is None else float(best_spread)
    return SPREAD_MARGIN * max(cur, best), defaulted


def classify_delta(
    delta: float, noise: float, threshold: float = 0.10
) -> str:
    """Label one signed relative delta (positive = worse).

    ``OK`` — at or better than best; ``WOBBLE`` — worse but within the
    noise floor (run-to-run variance explains it); ``REGRESSION`` —
    beyond ``max(threshold, REGRESSION_FACTOR × noise)`` (variance
    cannot explain it); ``WARN`` — the gap between (suspicious, rerun
    before trusting either way)."""
    if delta <= 0:
        return OK
    if delta <= noise:
        return WOBBLE
    if delta > max(threshold, REGRESSION_FACTOR * noise):
        return REGRESSION
    return WARN


def classify_capture(
    current: dict,
    history: Sequence[dict],
    threshold: float = 0.10,
) -> Tuple[bool, List[str], Dict[str, str]]:
    """Noise-aware gate: returns ``(ok, report_lines, labels)``.

    Same best-of-history comparison as :func:`check_capture`, but each
    guarded metric is labeled via :func:`classify_delta` with a noise
    floor from the current and best captures' recorded spreads
    (:func:`noise_floor`). ``ok`` is False only on REGRESSION — WOBBLE
    and WARN report loudly but do not fail the gate, so wall-clock
    wobble (r04→r05) cannot block an unrelated commit while a real 2×
    slowdown still does. ``labels`` maps metric name → label for the
    metrics actually compared."""
    lines: List[str] = []
    labels: Dict[str, str] = {}
    cur = extract_metrics(current)
    if cur is None:
        return (
            False,
            ["REGRESSION  current capture has no parsed bench metrics"],
            {},
        )
    entries = [
        (m, _spread_of(h), _env_of(h), _progprofile_of(h))
        for h, m in ((h, extract_metrics(h)) for h in history)
        if m
    ]
    if not entries:
        return False, ["REGRESSION  no usable history captures"], {}
    cur_spread = _spread_of(current)
    cur_env = _env_of(current)
    cur_pph = _progprofile_of(current)
    ok = True
    best_env: Optional[dict] = None
    best_pph: Optional[str] = None
    for name, direction in GUARDED_METRICS.items():
        vals = [
            (m[name], spread, env, pph)
            for m, spread, env, pph in entries
            if name in m
        ]
        if name not in cur or not vals:
            which = "current" if name not in cur else "history"
            lines.append(f"skip        {name}: no {which} value")
            continue
        pick = max if direction == "higher" else min
        best, b_spread, b_env, b_pph = pick(vals, key=lambda v: v[0])
        if best == 0:
            lines.append(f"skip        {name}: zero best in history")
            continue
        if name == "value":
            best_env = b_env
            best_pph = b_pph
        delta = (
            (best - cur[name]) / best
            if direction == "higher"
            else (cur[name] - best) / best
        )
        noise, defaulted = noise_floor(cur_spread, b_spread)
        label = classify_delta(delta, noise, threshold)
        labels[name] = label
        if label == REGRESSION:
            ok = False
        bound = max(threshold, REGRESSION_FACTOR * noise)
        lines.append(
            f"{label:<10}  {name}: current {cur[name]:.6g} vs best "
            f"{best:.6g} (Δ {-delta*100:+.1f}%, noise floor "
            f"{noise*100:.1f}%{' [default spread]' if defaulted else ''},"
            f" regress bound {bound*100:.1f}%, n_history={len(vals)})"
        )
    if cur_env is not None and best_env is not None:
        drift = [
            k
            for k in _FP_COMPARE_KEYS
            if cur_env.get(k) != best_env.get(k)
        ]
        if drift:
            lines.append(
                "note        env fingerprint drifted vs best capture: "
                + ", ".join(
                    f"{k} {best_env.get(k)!r}→{cur_env.get(k)!r}"
                    for k in drift
                )
            )
    elif cur_env is not None:
        lines.append(
            "note        best capture has no env fingerprint (predates"
            " it); deltas assume a comparable machine"
        )
    if (
        cur_pph is not None
        and best_pph is not None
        and cur_pph != best_pph
    ):
        lines.append(
            "note        static wire model changed between captures "
            f"(progprofile hash {best_pph!r}→{cur_pph!r}); a perf "
            "delta here may be the intentional wire/footprint change "
            "gated by progcheck J004, not a regression"
        )
    return ok, lines, labels


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Bench regression guard: compare a capture against "
        "committed BENCH_r*.json history (>threshold regressions fail)."
    )
    p.add_argument(
        "--current",
        help="capture to check (bench JSON line or BENCH_r wrapper); "
        "default: the newest history file, checked against the rest",
    )
    p.add_argument(
        "--history",
        default="BENCH_r*.json",
        help="glob of committed captures (default BENCH_r*.json)",
    )
    p.add_argument("--threshold", type=float, default=0.10)
    p.add_argument(
        "--legacy",
        action="store_true",
        help="use the pre-classifier binary gate (any >threshold delta "
        "fails) instead of the WOBBLE/WARN/REGRESSION classifier",
    )
    args = p.parse_args(argv)

    paths = sorted(glob.glob(args.history))
    if not paths:
        print(f"bench-check FAIL: no history matches {args.history!r}")
        return 2
    if args.current:
        current = _load(args.current)
        hist_paths = paths
    else:
        # self-test mode: newest (by round suffix = sorted order) vs rest
        current = _load(paths[-1])
        hist_paths = paths[:-1]
        if not hist_paths:
            print("bench-check ok: single capture, nothing to compare")
            return 0
        print(f"checking {paths[-1]} against {len(hist_paths)} earlier captures")
    history = [_load(pth) for pth in hist_paths]
    if args.legacy:
        ok, lines = check_capture(current, history, args.threshold)
        verdict = "ok" if ok else "FAIL"
    else:
        ok, lines, labels = classify_capture(
            current, history, args.threshold
        )
        worst = max(
            (label for label in labels.values()),
            key=lambda s: _SEVERITY[s],
            default=OK,
        )
        verdict = "FAIL (REGRESSION)" if not ok else (
            "ok" if worst == OK else f"ok ({worst})"
        )
    for ln in lines:
        print("  " + ln)
    print(f"bench-check {verdict}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
