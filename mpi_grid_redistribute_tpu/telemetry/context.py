"""Causal step context: thread-local attribution for journal events.

Every ``StepRecorder`` event answers *what* happened; this module makes
the envelope answer *on whose behalf*. A :class:`StepContext` is a tiny
host-side record — trace id, step index, redistribute call index,
restart attempt, origin thread — that the recorder merges into every
event it journals while the context is active on the recording thread
(``recorder._record_locked`` calls :func:`envelope_fields`). That turns
"which step caused this alert / restart / capacity_grow" into a join on
envelope fields instead of archaeology over interleaved seq numbers.

Contexts are immutable and cheap: the envelope dict is precomputed at
construction, so the per-event cost is one thread-local attribute load
plus a handful of ``setdefault``-style inserts — well inside the
recorder's committed <=2% overhead budget (``tests/test_metrics.py``).
Payload keys always win over context keys, so an event that already
carries ``step`` / ``attempt`` in its payload is never clobbered; the
context rides along under the ``trace`` / ``ctx_*`` names documented in
``telemetry/SCHEMA.md``.

Propagation is explicit, not ambient: thread-locals do not cross thread
boundaries, so code that hands work to another thread (the driver's
async snapshot writer, ``Supervisor`` restart attempts) captures
:func:`current` and activates a :meth:`StepContext.child` on the other
side. Children inherit the trace id — one trace spans the whole
supervised run, with ``ctx_attempt`` telling restart generations apart.

This module is on the scrape/capture path and must import neither jax
nor numpy; ``tests/test_metrics.py`` loads it standalone and asserts
jax never enters ``sys.modules``.
"""
# gridlint: scrape-path

from __future__ import annotations

import threading
import uuid
from typing import Dict, Optional

__all__ = [
    "StepContext",
    "activate",
    "current",
    "current_trace",
    "envelope_fields",
    "new_trace_id",
    "scoped",
    "use",
]

# Sentinel distinguishing "not passed" from an explicit None override in
# StepContext.child (child(step=None) clears the field; child() keeps it).
_UNSET = object()


def new_trace_id() -> str:
    """A fresh 12-hex-digit trace id (random; inject ids for tests)."""
    return uuid.uuid4().hex[:12]


class StepContext:
    """Immutable attribution record merged into journal envelopes.

    Fields:
      trace    correlation id shared by every event of one logical run
               (supervised run, demo loop, test); children inherit it.
      step     1-based simulation step the work belongs to, or None.
      call     ``GridRedistributor`` redistribute-call index, or None.
      attempt  supervisor restart attempt (0 = first), or None.
      origin   logical name of the thread/component that activated the
               context (defaults to the current thread's name).
    """

    __slots__ = ("trace", "step", "call", "attempt", "origin", "_envelope")

    def __init__(
        self,
        trace: Optional[str] = None,
        step: Optional[int] = None,
        call: Optional[int] = None,
        attempt: Optional[int] = None,
        origin: Optional[str] = None,
    ):
        object.__setattr__(
            self, "trace", new_trace_id() if trace is None else str(trace)
        )
        object.__setattr__(self, "step", None if step is None else int(step))
        object.__setattr__(self, "call", None if call is None else int(call))
        object.__setattr__(
            self, "attempt", None if attempt is None else int(attempt)
        )
        object.__setattr__(
            self,
            "origin",
            threading.current_thread().name if origin is None else str(origin),
        )
        env: Dict[str, object] = {"trace": self.trace}
        if self.step is not None:
            env["ctx_step"] = self.step
        if self.call is not None:
            env["ctx_call"] = self.call
        if self.attempt is not None:
            env["ctx_attempt"] = self.attempt
        env["ctx_origin"] = self.origin
        object.__setattr__(self, "_envelope", env)

    def __setattr__(self, name, value):
        raise AttributeError("StepContext is immutable; use child()")

    def envelope(self) -> Dict[str, object]:
        """The envelope fields this context contributes (do not mutate)."""
        return self._envelope

    def child(
        self,
        step=_UNSET,
        call=_UNSET,
        attempt=_UNSET,
        origin=_UNSET,
    ) -> "StepContext":
        """A derived context sharing this trace, with fields overridden.

        Unpassed fields are inherited; an explicit ``None`` clears the
        field (``origin=None`` re-derives from the current thread, which
        is what a cross-thread handoff usually wants).
        """
        return StepContext(
            trace=self.trace,
            step=self.step if step is _UNSET else step,
            call=self.call if call is _UNSET else call,
            attempt=self.attempt if attempt is _UNSET else attempt,
            origin=self.origin if origin is _UNSET else origin,
        )

    def __repr__(self) -> str:
        parts = [f"trace={self.trace!r}"]
        for name in ("step", "call", "attempt"):
            v = getattr(self, name)
            if v is not None:
                parts.append(f"{name}={v}")
        parts.append(f"origin={self.origin!r}")
        return f"StepContext({', '.join(parts)})"


_tls = threading.local()


def current() -> Optional[StepContext]:
    """The context active on this thread, or None."""
    return getattr(_tls, "ctx", None)


def current_trace() -> Optional[str]:
    """The active trace id on this thread, or None."""
    ctx = getattr(_tls, "ctx", None)
    return None if ctx is None else ctx.trace


def envelope_fields() -> Optional[Dict[str, object]]:
    """Envelope dict of the active context, or None. Recorder fast path.

    Callers treat the result as read-only — it is the context's own
    precomputed dict, not a copy.
    """
    ctx = getattr(_tls, "ctx", None)
    return None if ctx is None else ctx._envelope


def activate(ctx: Optional[StepContext]) -> Optional[StepContext]:
    """Make ``ctx`` this thread's active context; returns the previous one.

    Prefer the :class:`use` / :func:`scoped` context managers, which
    restore the previous context on exit even when the body raises.
    """
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


class use:
    """``with use(ctx): ...`` — activate ``ctx``, restore the previous
    context on exit (exception-safe). Reentrant and nestable."""

    def __init__(self, ctx: Optional[StepContext]):
        self._ctx = ctx
        self._prev: Optional[StepContext] = None

    def __enter__(self) -> Optional[StepContext]:
        self._prev = activate(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tls.ctx = self._prev
        return False


def scoped(**fields) -> use:
    """A :class:`use` over a child of the active context (or a fresh
    root when none is active), with ``fields`` overriding.

    The common one-liner for per-step / per-call scoping::

        with context.scoped(step=step):
            ... journal events carry ctx_step=step ...
    """
    cur = getattr(_tls, "ctx", None)
    ctx = cur.child(**fields) if cur is not None else StepContext(**fields)
    return use(ctx)
