"""Durable telemetry history: segmented on-disk journal store.

The :class:`~.recorder.StepRecorder` ring is deliberately bounded — old
events evict, journal shards die with the process, and the only thing
that survives a long run is the all-time per-kind counters. This module
is the layer that makes the journal *durable*: a
:class:`JournalStore` is a recorder **sink** — the service driver
drains the ring into it at chunk/health boundaries (never inside the
resident macro-step; the same G009 discipline every other host hook
keeps), and the store turns those drains into an append-only sequence
of on-disk **segments** with a checksummed manifest:

* **Segments** — JSONL files (the exact ``StepRecorder.to_jsonl`` line
  format, ``host``/``pid``-tagged) rotated on event count or byte size.
  Closed segments are immutable and carry a sha256 in the manifest.
* **Manifest** — one ``MANIFEST.json`` per store, published with the
  ``utils/checkpoint.py`` staged-rename idiom (write to a
  ``.tmp-<pid>`` sibling, fsync, atomic ``os.rename``): a reader either
  sees the previous complete manifest or the new complete one, never a
  torn mix. It carries the recorder's **exact all-time counts** — the
  PR 5 exactness claim, now durable: the counts survive ring eviction,
  segment retention AND process death.
* **Retention** — oldest closed segments are deleted when the store
  exceeds its byte budget or a segment ages out; their per-kind counts
  are folded into a ``retired`` tally so the count ledger stays exact.
* **Compaction** — closed raw segments are downsampled into summary
  segments: the per-step flood (``step_latency`` / ``step_time`` /
  ``migrate_step`` / ``fast_path`` / ``redistribute`` /
  ``flow_snapshot`` / ``state_health``) collapses into one
  ``store_window`` row per window carrying *exact* per-kind counts,
  step-latency/step-time histogram sketches on the metrics plane's own
  pow2 edges (``metrics.STEP_TIME_EDGES`` — so a quantile computed
  from a compacted store equals the one ``/metrics`` serves),
  dropped/mover totals, flow-imbalance samples and state-health
  corrupt-row totals, while every non-step event (alerts, incidents,
  snapshots, restores, faults, …) is preserved **verbatim**. A
  million-step run keeps bounded disk and exact all-time counts.

Every drain journals a ``store_drain`` event into the recorder it
drains — recorded *before* the snapshot is taken, so the drained
segment describes itself (telemetry/SCHEMA.md).

:class:`StoreReader` is the read side: ``events()`` yields the decoded
rows of every retained segment in order and ``counts()`` returns the
manifest's exact all-time totals, so a reader plugs straight into
``metrics.from_journal`` / ``query.rows_of`` / ``merge_journals``.

Scrape-path purity: host-only, stdlib + the jax-free metrics module —
never imports jax (G007; ``tests/test_metrics.py`` loads this module
with jax absent).
"""

from __future__ import annotations

# gridlint: scrape-path

import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, Optional

from . import metrics as metrics_lib

_MANIFEST = "MANIFEST.json"
_TMP_TAG = ".tmp-"
_SEG_PREFIX = "seg_"
_RAW_SUFFIX = ".jsonl"
_SUMMARY_SUFFIX = ".summary.jsonl"

#: Per-step event kinds compaction downsamples into ``store_window``
#: rows. Everything else (alerts, incidents, snapshots, restores,
#: faults, restarts, …) is operator-facing and preserved verbatim.
COMPACT_KINDS = frozenset(
    (
        "step_latency",
        "step_time",
        "migrate_step",
        "fast_path",
        "redistribute",
        "flow_snapshot",
        "state_health",
    )
)

#: Flow-imbalance samples kept per summary window (first/last plus the
#: extremes — enough to redraw the imbalance envelope per window).
_IMBALANCE_SAMPLES = 8


class StoreCorruptError(RuntimeError):
    """A store failed integrity checks: torn segment, checksum
    mismatch, or an unreadable manifest. ``member`` names the offending
    file (``MANIFEST.json`` when the manifest itself is bad)."""

    def __init__(self, root: str, member: str, detail: str):
        self.root = root
        self.member = member
        self.detail = detail
        super().__init__(
            f"corrupt journal store {root!r} ({member}): {detail}"
        )


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _merge_counts(into: Dict[str, int], add: Dict[str, int]) -> None:
    for k, n in add.items():
        into[k] = into.get(k, 0) + int(n)


def _sketch() -> dict:
    """Empty histogram sketch on the metrics plane's step-time edges:
    one slot per finite edge plus the +Inf overflow slot — the same
    layout ``metrics.Histogram`` keeps, so bucket counts merge 1:1."""
    return {
        "buckets": [0] * (len(metrics_lib.STEP_TIME_EDGES) + 1),
        "sum": 0.0,
        "count": 0,
    }


def _sketch_observe(sk: dict, value: float) -> None:
    v = float(value)
    sk["sum"] += v
    sk["count"] += 1
    for i, edge in enumerate(metrics_lib.STEP_TIME_EDGES):
        if v <= edge:
            sk["buckets"][i] += 1
            return
    sk["buckets"][-1] += 1


def sketch_to_histogram(sketches) -> metrics_lib.Histogram:
    """Merge ``store_window`` latency/step-time sketches into one
    ``metrics.Histogram`` on ``STEP_TIME_EDGES`` — the exact histogram
    a live recorder fed the same samples would have built, so
    ``quantile()`` answers match ``/metrics`` bucket-for-bucket."""
    h = metrics_lib.Histogram((), metrics_lib.STEP_TIME_EDGES)
    for sk in sketches:
        if not sk or not sk.get("count"):
            continue
        for i, n in enumerate(sk["buckets"]):
            h._bucket_counts[i] += int(n)
        h._sum += float(sk["sum"])
        h._count += int(sk["count"])
    return h


class JournalStore:
    """Write side: an append-only segmented store, drained from a live
    :class:`~.recorder.StepRecorder`.

    One store root has ONE writer (the service driver's main thread —
    the same single-writer discipline the recorder's T005 contract
    declares); a restarted driver re-opens the same root and resumes
    from the manifest's drain watermark, so supervisor restarts never
    duplicate events. Readers (:class:`StoreReader`, ``storecheck``,
    ``grid_top``) only ever see atomically-published manifests.
    """

    def __init__(
        self,
        root: str,
        segment_events: int = 4096,
        segment_bytes: int = 4 << 20,
        retain_bytes: int = 64 << 20,
        retain_age_s: float = 0.0,
        compact_after: int = 2,
        compact_window: int = 256,
    ):
        if segment_events < 1:
            raise ValueError(
                f"segment_events must be >= 1, got {segment_events}"
            )
        if compact_window < 1:
            raise ValueError(
                f"compact_window must be >= 1, got {compact_window}"
            )
        self.root = str(root)
        self.segment_events = int(segment_events)
        self.segment_bytes = int(segment_bytes)
        self.retain_bytes = int(retain_bytes)
        self.retain_age_s = float(retain_age_s)
        self.compact_after = int(compact_after)
        self.compact_window = int(compact_window)
        os.makedirs(self.root, exist_ok=True)
        man = self._load_manifest()
        if man is None:
            man = {
                "version": 1,
                "created": time.time(),
                "updated": time.time(),
                "writer": None,
                "drained_seq": 0,
                "drains": 0,
                # exact all-time per-kind counts: the recorder's own
                # counter snapshot at the latest drain
                "counts": {},
                # per-kind events the ring evicted BETWEEN drains (never
                # persisted; the gap between counts and segment sums)
                "missed": {},
                # per-kind counts folded out of retention-deleted
                # segments (the events are gone, the ledger is not)
                "retired": {"segments": 0, "bytes": 0, "counts": {}},
                "segments": [],
                "active": None,
                "config": {
                    "segment_events": self.segment_events,
                    "segment_bytes": self.segment_bytes,
                    "retain_bytes": self.retain_bytes,
                    "retain_age_s": self.retain_age_s,
                    "compact_after": self.compact_after,
                    "compact_window": self.compact_window,
                },
            }
        self._man = man

    # ------------------------------------------------------- manifest

    def _load_manifest(self) -> Optional[dict]:
        path = os.path.join(self.root, _MANIFEST)
        if not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise StoreCorruptError(self.root, _MANIFEST, str(e)) from e

    def _publish_manifest(self) -> None:
        # the checkpoint.py staged-rename idiom, file-shaped: stage in a
        # .tmp-<pid> sibling, fsync, then one atomic os.rename — a
        # reader sees the previous complete manifest or this one, never
        # a torn mix
        self._man["updated"] = time.time()
        path = os.path.join(self.root, _MANIFEST)
        tmp = f"{path}{_TMP_TAG}{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._man, f, indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)

    # ---------------------------------------------------------- drain

    def drain(self, recorder) -> int:
        """Append every retained event newer than the drain watermark;
        publish the manifest. Returns the number of events persisted.

        The drain journals itself FIRST (``store_drain``, before the
        snapshot is taken), so the persisted window includes its own
        drain event and the manifest's count snapshot equals the live
        recorder's counts at the drain instant — the property the
        counts-exactness test pins end to end. Events the ring evicted
        between drains are impossible to persist; their per-kind counts
        land in the manifest's ``missed`` ledger instead of vanishing.
        """
        man = self._man
        active = self._ensure_active(recorder)
        recorder.record(
            "store_drain",
            segment=active["name"],
            after_seq=int(man["drained_seq"]),
        )
        # snapshot order matters: events first, then counts — counts
        # taken after can only be >= what the window shows, so the
        # missed ledger never under-counts (clamped at 0 per kind)
        events = recorder.events()
        counts = recorder.counts()
        # All-time counts are monotone for any recorder that has been
        # draining into this store; a per-kind regression proves a NEW
        # recorder incarnation whose seq space restarts below the
        # watermark — its events would be silently skipped and then
        # booked as missed. Refuse loudly instead of losing data.
        regressed = {
            k: (int(man["counts"][k]), int(counts.get(k, 0)))
            for k in man["counts"]
            if int(counts.get(k, 0)) < int(man["counts"][k])
        }
        if regressed:
            raise ValueError(
                "store drain: recorder all-time counts regressed vs the "
                f"manifest at {self.root} ({regressed}; manifest, "
                "recorder) — this recorder is a different incarnation "
                "from the store's writer. Resume with the original "
                "recorder (or one rebuilt via StoreReader.to_recorder), "
                "or start a fresh store directory."
            )
        tags = {"host": recorder.host, "pid": recorder.pid}
        watermark = int(man["drained_seq"])
        new = [e for e in events if e.seq > watermark]
        if new:
            seg_path = os.path.join(self.root, active["name"])
            with open(seg_path, "a", encoding="utf-8") as f:
                for e in new:
                    f.write(e.to_json(tags) + "\n")
                f.flush()
                os.fsync(f.fileno())
            active["events"] += len(new)
            active["bytes"] = os.path.getsize(seg_path)
            active["seq_min"] = (
                min(active["seq_min"], new[0].seq)
                if active["seq_min"] is not None
                else new[0].seq
            )
            active["seq_max"] = new[-1].seq
            active["time_min"] = (
                min(active["time_min"], new[0].time)
                if active["time_min"] is not None
                else new[0].time
            )
            active["time_max"] = new[-1].time
            for e in new:
                active["counts"][e.kind] = (
                    active["counts"].get(e.kind, 0) + 1
                )
            man["drained_seq"] = new[-1].seq
        # missed ledger: counts delta not covered by persisted events
        prev = man["counts"]
        stored: Dict[str, int] = {}
        for e in new:
            stored[e.kind] = stored.get(e.kind, 0) + 1
        for kind, total in counts.items():
            gap = (
                int(total) - int(prev.get(kind, 0)) - stored.get(kind, 0)
            )
            if gap > 0:
                man["missed"][kind] = man["missed"].get(kind, 0) + gap
        man["counts"] = dict(counts)
        man["writer"] = {"host": recorder.host, "pid": recorder.pid}
        man["drains"] = int(man.get("drains", 0)) + 1
        if (
            active["events"] >= self.segment_events
            or active["bytes"] >= self.segment_bytes
        ):
            self._rotate()
        self._publish_manifest()
        self.compact()
        self.retention()
        return len(new)

    def _ensure_active(self, recorder) -> dict:
        man = self._man
        if man["active"] is None:
            idx = len(man["segments"]) + man["retired"]["segments"]
            # segment numbering never reuses a retired slot: names stay
            # globally ordered across the store's whole life
            existing = [
                int(s["name"][len(_SEG_PREFIX):][:8])
                for s in man["segments"]
            ]
            if existing:
                idx = max(idx, max(existing) + 1)
            man["active"] = {
                "name": f"{_SEG_PREFIX}{idx:08d}{_RAW_SUFFIX}",
                "events": 0,
                "bytes": 0,
                "seq_min": None,
                "seq_max": None,
                "time_min": None,
                "time_max": None,
                "counts": {},
            }
        return man["active"]

    def _rotate(self) -> None:
        """Close the active segment: checksum it and move it to the
        closed list. The sha256 is computed over the final bytes —
        immutable from here on (``storecheck`` re-verifies it)."""
        man = self._man
        active = man["active"]
        if active is None or active["events"] == 0:
            man["active"] = None
            return
        path = os.path.join(self.root, active["name"])
        entry = dict(active)
        entry["kind"] = "raw"
        entry["sha256"] = _sha256_file(path)
        entry["closed"] = time.time()
        man["segments"].append(entry)
        man["active"] = None

    # ----------------------------------------------------- compaction

    def compact(self, keep_raw: Optional[int] = None) -> int:
        """Downsample closed raw segments into summary segments,
        keeping the newest ``keep_raw`` (default ``compact_after``) raw.
        Returns the number of segments compacted.

        Each summary preserves non-step events verbatim and collapses
        the per-step kinds into ``store_window`` rows (exact per-kind
        counts, latency/step-time sketches on ``STEP_TIME_EDGES``,
        dropped/mover totals, flow-imbalance samples). The summary is
        fully written and checksummed, the manifest republished, and
        only then is the raw file removed — a crash between the two
        leaves a harmless orphan, never a hole.
        """
        keep = self.compact_after if keep_raw is None else int(keep_raw)
        man = self._man
        raw = [s for s in man["segments"] if s["kind"] == "raw"]
        todo = raw[: max(0, len(raw) - keep)]
        done = 0
        for entry in todo:
            summary = self._compact_segment(entry)
            i = man["segments"].index(entry)
            man["segments"][i] = summary
            self._publish_manifest()
            os.remove(os.path.join(self.root, entry["name"]))
            done += 1
        return done

    def _compact_segment(self, entry: dict) -> dict:
        src = os.path.join(self.root, entry["name"])
        rows: List[dict] = []
        with open(src, encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    rows.append(json.loads(ln))
        out_name = entry["name"][: -len(_RAW_SUFFIX)] + _SUMMARY_SUFFIX
        out_path = os.path.join(self.root, out_name)
        windows = 0
        counts: Dict[str, int] = {}
        with open(out_path, "w", encoding="utf-8") as f:
            window: List[dict] = []
            for r in rows:
                counts[r["kind"]] = counts.get(r["kind"], 0) + 1
                if r["kind"] in COMPACT_KINDS:
                    window.append(r)
                    if len(window) >= self.compact_window:
                        f.write(self._window_row(window) + "\n")
                        windows += 1
                        window = []
                else:
                    # verbatim: alerts, incidents, snapshots, restores,
                    # faults, restarts, store_drain, … keep every byte
                    f.write(json.dumps(r, sort_keys=True) + "\n")
            if window:
                f.write(self._window_row(window) + "\n")
                windows += 1
            f.flush()
            os.fsync(f.fileno())
        summary = {
            "name": out_name,
            "kind": "summary",
            "source": entry["name"],
            "source_sha256": entry["sha256"],
            "events": entry["events"],
            "bytes": os.path.getsize(out_path),
            "seq_min": entry["seq_min"],
            "seq_max": entry["seq_max"],
            "time_min": entry["time_min"],
            "time_max": entry["time_max"],
            "counts": counts,
            "windows": windows,
            "sha256": _sha256_file(out_path),
            "closed": entry.get("closed"),
            "compacted": time.time(),
        }
        return summary

    @staticmethod
    def _window_row(window: List[dict]) -> str:
        """One ``store_window`` summary row for a run of per-step
        events: exact per-kind counts, histogram sketches on the
        metrics plane's edges, totals, and flow-imbalance samples
        (SCHEMA.md "Telemetry history store")."""
        counts: Dict[str, int] = {}
        latency = _sketch()
        step_time = _sketch()
        dropped_total = 0
        dropped_max = 0
        fp_taken = 0
        fp_total = 0
        movers_max = 0
        migrate = {"sent": 0, "received": 0, "dropped_recv": 0}
        backlog_last = None
        population_last = None
        state = {"nan_pos": 0, "nan_vel": 0, "oob": 0}
        state_live_last = None
        state_residual_last = None
        saw_state = False
        step_min = None
        step_max = None
        imbalance: List[List[float]] = []
        for r in window:
            kind = r["kind"]
            counts[kind] = counts.get(kind, 0) + 1
            step = r.get("step")
            if step is not None:
                step_min = step if step_min is None else min(step_min, step)
                step_max = step if step_max is None else max(step_max, step)
            if kind == "step_latency":
                if "seconds" in r:
                    _sketch_observe(latency, r["seconds"])
                d = int(r.get("dropped", 0))
                dropped_total += d
                dropped_max = max(dropped_max, d)
            elif kind == "step_time":
                if "seconds" in r:
                    _sketch_observe(step_time, r["seconds"])
            elif kind == "fast_path":
                fp_total += 1
                fp_taken += int(r.get("taken", 0))
                movers_max = max(movers_max, int(r.get("movers", 0)))
            elif kind == "migrate_step":
                for key in migrate:
                    migrate[key] += int(r.get(key, 0))
                if "backlog" in r:
                    backlog_last = int(r["backlog"])
                if "population" in r:
                    population_last = int(r["population"])
            elif kind == "flow_snapshot":
                if "imbalance" in r:
                    imbalance.append(
                        [float(r.get("time", 0.0)), float(r["imbalance"])]
                    )
            elif kind == "state_health":
                saw_state = True
                for key in state:
                    state[key] += int(r.get(key, 0))
                if "live" in r:
                    state_live_last = int(r["live"])
                if "residual" in r:
                    state_residual_last = int(r["residual"])
        if len(imbalance) > _IMBALANCE_SAMPLES:
            # keep first/last and the extremes: enough to redraw the
            # per-window imbalance envelope without the full series
            by_val = sorted(imbalance[1:-1], key=lambda s: s[1])
            keep = (
                [imbalance[0]]
                + by_val[: (_IMBALANCE_SAMPLES - 2) // 2]
                + by_val[-((_IMBALANCE_SAMPLES - 2) // 2):]
                + [imbalance[-1]]
            )
            imbalance = sorted(keep, key=lambda s: s[0])
        doc = {
            "kind": "store_window",
            "seq": window[0].get("seq"),
            "seq_max": window[-1].get("seq"),
            "time": window[0].get("time"),
            "time_max": window[-1].get("time"),
            "host": window[0].get("host"),
            "pid": window[0].get("pid"),
            "events": len(window),
            "counts": counts,
            "latency": latency,
            "step_time": step_time,
            "dropped": {"total": dropped_total, "max": dropped_max},
            "fast_path": {
                "taken": fp_taken,
                "total": fp_total,
                "movers_max": movers_max,
            },
            "migrate": dict(
                migrate,
                backlog_last=backlog_last,
                population_last=population_last,
            ),
            "imbalance": imbalance,
        }
        if saw_state:
            # corrupt-row totals are exact across compaction; the
            # latest ledger gauges ride along so grid_state_live_rows /
            # grid_state_residual survive the raw rows' deletion
            doc["state"] = dict(
                state,
                live_last=state_live_last,
                residual_last=state_residual_last,
            )
        if step_min is not None:
            doc["step_min"] = step_min
            doc["step_max"] = step_max
        return json.dumps(doc, sort_keys=True)

    # ------------------------------------------------------ retention

    def retention(self) -> int:
        """Delete oldest closed segments over the byte budget (or past
        ``retain_age_s``); fold their counts into the ``retired``
        ledger. Returns segments deleted. The manifest's all-time
        ``counts`` are a recorder snapshot, so exactness is unaffected
        — retention trades *detail* for disk, never totals."""
        man = self._man
        deleted = 0
        now = time.time()
        while man["segments"]:
            total = sum(s["bytes"] for s in man["segments"])
            oldest = man["segments"][0]
            over_bytes = total > self.retain_bytes
            over_age = (
                self.retain_age_s > 0
                and oldest.get("time_max") is not None
                and now - oldest["time_max"] > self.retain_age_s
            )
            if not (over_bytes or over_age):
                break
            man["segments"].pop(0)
            man["retired"]["segments"] += 1
            man["retired"]["bytes"] += oldest["bytes"]
            _merge_counts(man["retired"]["counts"], oldest["counts"])
            self._publish_manifest()
            path = os.path.join(self.root, oldest["name"])
            if os.path.exists(path):
                os.remove(path)
            deleted += 1
        return deleted

    # ---------------------------------------------------------- close

    def close(self, recorder=None) -> None:
        """Orderly shutdown: final drain (when given the recorder),
        close the active segment, compact, enforce retention, publish."""
        if recorder is not None:
            self.drain(recorder)
        self._rotate()
        self._publish_manifest()
        self.compact()
        self.retention()

    # -------------------------------------------------------- queries

    @property
    def manifest(self) -> dict:
        return self._man

    def reader(self) -> "StoreReader":
        return StoreReader(self.root)


class StoreReader:
    """Read side: decoded event rows + exact all-time counts.

    Duck-compatible with the journal sources ``metrics.from_journal``
    and ``query.rows_of`` accept (``events()`` + ``counts()``), so the
    whole single-process observability stack runs over a store on disk
    the same way it runs over a live ring."""

    def __init__(self, root: str, verify: bool = False):
        self.root = str(root)
        path = os.path.join(self.root, _MANIFEST)
        try:
            with open(path, encoding="utf-8") as f:
                self._man = json.load(f)
        except (OSError, ValueError) as e:
            raise StoreCorruptError(self.root, _MANIFEST, str(e)) from e
        for key in ("counts", "segments"):
            if key not in self._man:
                raise StoreCorruptError(
                    self.root, _MANIFEST, f"missing manifest key {key!r}"
                )
        if verify:
            self.verify()

    @property
    def manifest(self) -> dict:
        return self._man

    def verify(self) -> None:
        """Checksum every closed segment against the manifest; raise
        :class:`StoreCorruptError` naming the first bad one."""
        for seg in self._man["segments"]:
            path = os.path.join(self.root, seg["name"])
            if not os.path.exists(path):
                raise StoreCorruptError(
                    self.root, seg["name"], "segment file missing"
                )
            got = _sha256_file(path)
            if got != seg["sha256"]:
                raise StoreCorruptError(
                    self.root,
                    seg["name"],
                    f"sha256 mismatch: manifest {seg['sha256'][:12]}…, "
                    f"file {got[:12]}…",
                )

    def _segment_files(self) -> List[str]:
        names = [s["name"] for s in self._man["segments"]]
        active = self._man.get("active")
        if active is not None:
            names.append(active["name"])
        return names

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Every retained row (verbatim events AND ``store_window``
        summaries), decoded, in store order; optionally filtered by
        kind. Rows keep their full envelope (``seq``/``time``/``host``/
        ``pid``)."""
        rows: List[dict] = []
        for name in self._segment_files():
            path = os.path.join(self.root, name)
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        d = json.loads(ln)
                    except ValueError as e:
                        raise StoreCorruptError(
                            self.root, name, f"bad JSONL line: {e}"
                        ) from e
                    if kind is None or d.get("kind") == kind:
                        rows.append(d)
        return rows

    def counts(self) -> Dict[str, int]:
        """Exact all-time per-kind counts — the recorder's own counter
        snapshot at the last drain. Survives ring eviction, segment
        retention and compaction (the store's reason to exist)."""
        return dict(self._man["counts"])

    def latency_histogram(self) -> metrics_lib.Histogram:
        """One merged step-latency histogram over the whole retained
        store: raw ``step_latency`` rows observed directly, compacted
        windows merged sketch-for-sketch — both on ``STEP_TIME_EDGES``,
        so the answer equals a live histogram fed the same samples."""
        h = metrics_lib.Histogram((), metrics_lib.STEP_TIME_EDGES)
        sketches = []
        for r in self.events():
            if r.get("kind") == "step_latency" and "seconds" in r:
                h.observe(float(r["seconds"]))
            elif r.get("kind") == "store_window":
                sketches.append(r.get("latency"))
        merged = sketch_to_histogram(sketches)
        for i, n in enumerate(merged._bucket_counts):
            h._bucket_counts[i] += n
        h._sum += merged._sum
        h._count += merged._count
        return h

    def to_recorder(self, capacity: Optional[int] = None):
        """Replay the retained rows into a fresh ``StepRecorder`` (host
        tag ``"store"``) and pin its all-time counters to the
        manifest's exact totals, so ``HealthMonitor`` / ``from_journal``
        over the replay see the same counts the live run had. The
        replay is single-threaded construction — the counter overwrite
        happens before the recorder is shared anywhere."""
        from . import recorder as recorder_lib

        rows = [r for r in self.events() if r.get("kind") != "store_window"]
        cap = capacity if capacity is not None else max(4096, 2 * len(rows))
        rec = recorder_lib.StepRecorder(capacity=cap, host="store", pid=0)
        for r in rows:
            d = {
                k: v
                for k, v in r.items()
                if k not in ("seq", "time", "kind")
            }
            rec.record_at(r["kind"], r.get("time"), **d)
        with rec._lock:
            rec._counts.clear()
            rec._counts.update(
                {k: int(v) for k, v in self._man["counts"].items()}
            )
        return rec


def is_store(root: str) -> bool:
    """True when ``root`` looks like a journal store (has a manifest)."""
    return os.path.isfile(os.path.join(root, _MANIFEST))


def list_stores(root: str) -> List[str]:
    """Store roots anywhere under ``root`` (including ``root`` itself),
    sorted by manifest mtime, newest first — the run index ``scripts/
    history.py`` walks. Descent stops at each store found (segments
    are never themselves stores), so run layouts like
    ``runs/<run>/store`` index at any nesting depth."""
    out = []
    for dirpath, dirnames, _ in os.walk(root):
        if is_store(dirpath):
            out.append(dirpath)
            dirnames[:] = []
        else:
            dirnames.sort()
    out.sort(
        key=lambda p: os.stat(os.path.join(p, _MANIFEST)).st_mtime_ns,
        reverse=True,
    )
    return out


def wipe(root: str) -> None:
    """Remove a store directory (tests / demo teardown)."""
    shutil.rmtree(root, ignore_errors=True)
