"""The metrics surface: one merged dict per exchange workload.

The BASELINE metric is two-headed — "particles/sec/chip; ICI all_to_all
BW utilization" — and before this module the utilization half lived as a
hand-assembled expression in bench.py while the stats summaries lived in
:mod:`..utils.stats`. :func:`exchange_report` merges the whole surface:
stats summary, exchange bytes/step (total and moved/off-diagonal),
achieved GB/s, ``bw_util`` against the domain roof
(:func:`..utils.profiling.exchange_peak_bytes_per_sec`), and the
recorder's growth/overflow event counts. ``GridRedistribute.report()``
and every bench driver emit this dict, so the same numbers appear in
tests, bench JSON and operator logs.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

from mpi_grid_redistribute_tpu.telemetry import flow as flow_lib
from mpi_grid_redistribute_tpu.utils import profiling, stats as stats_lib


def row_bytes_of(positions, *fields) -> int:
    """Payload bytes one particle row carries across the exchange.

    Sums position components plus every field's trailing elements, each
    at its own itemsize — valid for both engine layouts, since planar
    ``[K, n]`` and row-major ``[n, K]`` move the same logical row, only
    tiled differently. Accepts anything with ``.shape``/``.dtype``
    (arrays or ShapeDtypeStructs)."""
    total = 0
    for a in (positions, *fields):
        per_row = int(np.prod(a.shape[1:])) if len(a.shape) > 1 else 1
        total += per_row * np.dtype(a.dtype).itemsize
    return total


def _moved_bytes_per_step(stats, row_bytes: int) -> float:
    """Mean OFF-DIAGONAL bytes/step: rows that changed ranks.

    ``RedistributeStats.send_counts`` ``[..., R, R]`` includes the
    diagonal (rows a rank keeps); those never cross the inter-chip wire,
    so the ICI utilization divides moved bytes only. ``MigrateStats.sent``
    already counts movers exclusively."""
    if hasattr(stats, "sent"):
        return profiling.exchange_bytes_per_step(stats, row_bytes)
    send = np.asarray(stats.send_counts)
    send = send.reshape(-1, send.shape[-2], send.shape[-1])
    moved = send.sum(axis=(1, 2)) - np.einsum("sii->s", send)
    return float(moved.mean()) * row_bytes


def exchange_report(
    stats,
    row_bytes: int,
    *,
    step_seconds: Optional[float] = None,
    domain: str = "hbm",
    n_chips: int = 1,
    recorder=None,
    engine_wire_cols: Optional[int] = None,
    dense_wire_cols: Optional[int] = None,
    wire_shards: Optional[int] = None,
) -> Dict[str, object]:
    """Merged metrics dict for one exchange workload.

    Args:
      stats: a ``RedistributeStats`` or ``MigrateStats`` pytree (single
        call or step-stacked) — the kind is detected and summarized with
        the matching :mod:`..utils.stats` summary.
      row_bytes: payload bytes per row (:func:`row_bytes_of`).
      step_seconds: honest per-step seconds — pass a scan-differenced
        measurement (:func:`..utils.profiling.scan_time_per_step`);
        without it the byte totals are reported but the rate/utilization
        fields are ``None`` (a wall-clock guess would overstate dispatch
        overhead as wire time, so none is silently substituted).
      domain: ``"hbm"`` (single-chip vrank exchange) or ``"ici"``
        (multi-chip all_to_all) — selects the roof AND which byte count
        utilization divides: HBM moves every gathered/scattered row,
        the ICI wire only the moved (off-diagonal) ones.
      n_chips: chips sharing the aggregate byte rate.
      recorder: optional :class:`..telemetry.recorder.StepRecorder`; its
        all-time per-kind counts land under ``"events"``.
      engine_wire_cols / dense_wire_cols / wire_shards: the scheduled
        wire model of the dispatched canonical engine — per-shard pool
        columns the exchange collective actually moves, the dense
        ``R * capacity`` columns it replaced, and the shard count.
        When given, ``wire_bytes_per_step`` reports the SCHEDULED bytes
        on the wire (pool width x row bytes x shards; fallback steps
        folded in at the dense width) — distinct from
        ``moved_bytes_per_step``, which counts occupied rows only. The
        count-driven engines shrink the former toward the latter.

    The dict is JSON-serializable (plain floats/ints/strs/dicts).
    """
    is_migrate = hasattr(stats, "sent")
    summary = (
        stats_lib.summarize_migrate(stats)
        if is_migrate
        else stats_lib.summarize_redistribute(stats)
    )
    total_bps = profiling.exchange_bytes_per_step(stats, row_bytes)
    moved_bps = _moved_bytes_per_step(stats, row_bytes)
    wire_bytes = moved_bps if domain == "ici" else total_bps
    out: Dict[str, object] = {
        "kind": "migrate" if is_migrate else "redistribute",
        "stats": summary,
        "row_bytes": int(row_bytes),
        "exchange_bytes_per_step": total_bps,
        "moved_bytes_per_step": moved_bps,
        "exchange_domain": domain,
        "n_chips": int(n_chips),
        "step_seconds": step_seconds,
        "exchange_bytes_per_sec": None,
        "exchange_gb_per_sec": None,
        "bw_util": None,
    }
    if step_seconds is not None and step_seconds > 0:
        bps = wire_bytes / step_seconds
        out["exchange_bytes_per_sec"] = bps
        out["exchange_gb_per_sec"] = bps / 1e9
        out["bw_util"] = profiling.exchange_bw_util(bps, domain, n_chips)
    # per-link refinement (telemetry.flow): mean per-step flow matrix ->
    # hottest pairs with per-link moved bytes and bw_util against ONE
    # link's roof. Aggregate-only stats (a hand-built MigrateStats with
    # flow=None) simply omit the section.
    try:
        mean_matrix = flow_lib.flow_matrix_of(stats).mean(axis=0)
    except (ValueError, TypeError):
        mean_matrix = None
    if mean_matrix is not None:
        out["links"] = flow_lib.link_report(
            mean_matrix, row_bytes, step_seconds=step_seconds, domain=domain
        )
    # sparse fast-path hit rate (ISSUE 4): present whenever the stats
    # came from a sparse-capable loop (fast_path leaf is a [S, R] 1/0
    # guard trace; dense-only loops carry None and omit the field).
    fp = getattr(stats, "fast_path", None)
    if fp is not None:
        fp = np.asarray(fp).reshape(-1, np.asarray(fp).shape[-1])
        taken = int(np.count_nonzero(fp.any(axis=1)))
        out["fast_path_steps"] = taken
        out["fast_path_hit_rate"] = taken / fp.shape[0] if fp.shape[0] else None
    # software-pipelined branch trace (ISSUE 12): `pipeline` is a
    # [..., R] 1/0 trace on the pipelined resident engine's stats (1 =
    # that step's exchange armed for overlapped consumption); every
    # other engine carries None and omits the pair. Mirrors fast_path_*
    # so operators can see how often the pipelined branch actually ran.
    pl = getattr(stats, "pipeline", None)
    if pl is not None:
        pl = np.asarray(pl).reshape(-1, np.asarray(pl).shape[-1])
        hit = int(np.count_nonzero(pl.any(axis=1)))
        out["pipeline_steps"] = hit
        out["pipeline_hit_rate"] = hit / pl.shape[0] if pl.shape[0] else None
    # count-driven fallback trace (ISSUE 7): `fallback` is a [..., R] 1/0
    # guard trace on sparse/neighbor canonical stats (1 = that step took
    # the dense in-graph fallback); dense engines carry None and omit
    # the section. Any rank falling back means ALL did (the pmin guard).
    fb_rate = 0.0
    fb = getattr(stats, "fallback", None)
    if fb is not None:
        fb = np.asarray(fb).reshape(-1, np.asarray(fb).shape[-1])
        fell = int(np.count_nonzero(fb.any(axis=1)))
        out["fallback_steps"] = fell
        out["fallback_rate"] = fell / fb.shape[0] if fb.shape[0] else None
        fb_rate = fell / fb.shape[0] if fb.shape[0] else 0.0
    # scheduled wire-cost model (ISSUE 7): what the exchange collective
    # puts on the wire regardless of occupancy; fallback steps billed at
    # the dense width they actually ran at
    if engine_wire_cols is not None and wire_shards is not None:
        cols = float(engine_wire_cols)
        if dense_wire_cols is not None:
            dense_bps = float(dense_wire_cols) * row_bytes * int(wire_shards)
            out["dense_wire_bytes_per_step"] = dense_bps
            cols = cols * (1.0 - fb_rate) + float(dense_wire_cols) * fb_rate
        out["wire_bytes_per_step"] = cols * row_bytes * int(wire_shards)
    if recorder is not None:
        out["events"] = recorder.counts()
        out["events_evicted"] = recorder.evicted
    return out


def format_report(report: Dict[str, object]) -> str:
    """One human line from an :func:`exchange_report` dict."""
    bw = report.get("bw_util")
    gbs = report.get("exchange_gb_per_sec")
    rate = (
        "rate: pass step_seconds"
        if gbs is None
        else f"{gbs:.2f} GB/s ({bw*100:.2f}% of {report['exchange_domain']})"
    )
    ev = report.get("events") or {}
    grows = ev.get("capacity_grow", 0) + ev.get("halo_grow", 0)
    return (
        f"{report['kind']}: {report['exchange_bytes_per_step']/1e6:.2f} "
        f"MB/step ({report['moved_bytes_per_step']/1e6:.2f} moved), "
        f"{rate}, grows={grows}"
    )
